"""Safe expression language + templates for agent configuration.

Parity: the reference evaluates ``when:`` guards and field expressions with
JSTL/EL (``langstream-agents-commons/.../jstl/JstlEvaluator.java`` +
``JstlFunctions.java``) and renders prompts with Mustache
(``ChatCompletionsStep.java`` message templating). Here:

- :func:`evaluate` — a whitelisted-AST Python-expression evaluator over the
  record context (``value``, ``key``, ``properties``, plus ``fn.*`` helper
  functions). No attribute access on arbitrary objects, no calls except
  whitelisted helpers: safe against config-injection.
- :func:`render_template` — a minimal Mustache renderer: ``{{ path }}``
  interpolation, ``{{# path }}…{{/ path}}`` sections (lists & truthiness),
  ``{{^ path}}`` inverted sections.

Expressions accept both EL-ish dotted paths (``value.question``) and Python
operators (``==``, ``&&``→``and`` is normalised).
"""

from __future__ import annotations

import ast
import json
import re
from typing import Any, Mapping

from langstream_tpu.api.record import MutableRecord

_ALLOWED_NODES = (
    ast.Expression,
    ast.BoolOp, ast.And, ast.Or,
    ast.UnaryOp, ast.Not, ast.USub, ast.UAdd,
    ast.BinOp, ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod,
    ast.Pow,
    ast.Compare, ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
    ast.In, ast.NotIn, ast.Is, ast.IsNot,
    ast.IfExp,
    ast.Call,
    ast.Attribute,
    ast.Subscript, ast.Index if hasattr(ast, "Index") else ast.Subscript,
    ast.Name, ast.Load,
    ast.Constant,
    ast.List, ast.Tuple, ast.Dict,
    ast.Slice,
)


class _Fn:
    """Whitelisted helper functions (parity: ``JstlFunctions.java``)."""

    @staticmethod
    def lowercase(s: Any) -> Any:
        return s.lower() if isinstance(s, str) else s

    @staticmethod
    def uppercase(s: Any) -> Any:
        return s.upper() if isinstance(s, str) else s

    @staticmethod
    def trim(s: Any) -> Any:
        return s.strip() if isinstance(s, str) else s

    @staticmethod
    def concat(*parts: Any) -> str:
        return "".join("" if p is None else str(p) for p in parts)

    @staticmethod
    def contains(haystack: Any, needle: Any) -> bool:
        try:
            return needle in haystack
        except TypeError:
            return False

    @staticmethod
    def coalesce(*vals: Any) -> Any:
        for v in vals:
            if v is not None:
                return v
        return None

    @staticmethod
    def split(s: Any, sep: str = ",") -> list:
        return s.split(sep) if isinstance(s, str) else []

    @staticmethod
    def replace(s: Any, old: str, new: str) -> Any:
        return s.replace(old, new) if isinstance(s, str) else s

    @staticmethod
    def len(x: Any) -> int:
        try:
            return len(x)
        except TypeError:
            return 0

    @staticmethod
    def str(x: Any) -> str:
        return "" if x is None else str(x)

    @staticmethod
    def toJson(x: Any) -> str:
        return json.dumps(x)

    @staticmethod
    def fromJson(s: Any) -> Any:
        return json.loads(s) if isinstance(s, str) else s

    @staticmethod
    def toInt(x: Any) -> int | None:
        try:
            return int(x)
        except (TypeError, ValueError):
            return None

    @staticmethod
    def toDouble(x: Any) -> float | None:
        try:
            return float(x)
        except (TypeError, ValueError):
            return None

    @staticmethod
    def startsWith(s: Any, prefix: str) -> bool:
        return isinstance(s, str) and s.startswith(prefix)

    @staticmethod
    def endsWith(s: Any, suffix: str) -> bool:
        return isinstance(s, str) and s.endswith(suffix)


class _DotDict(dict):
    """dict whose attribute access falls through to keys, so both
    ``value['a']`` and ``value.a`` work in expressions; missing keys are
    ``None`` (EL semantics, not KeyError)."""

    def __getattr__(self, name: str) -> Any:
        return _wrap(self.get(name))

    def __getitem__(self, name: Any) -> Any:
        return _wrap(self.get(name) if isinstance(name, str) else dict.get(self, name))


def _wrap(obj: Any) -> Any:
    if isinstance(obj, Mapping) and not isinstance(obj, _DotDict):
        return _DotDict(obj)
    if isinstance(obj, list):
        return [_wrap(o) for o in obj]
    return obj


class ExpressionError(ValueError):
    pass


_EL_NORMALISE = [
    (re.compile(r"&&"), " and "),
    (re.compile(r"\|\|"), " or "),
    (re.compile(r"(?<![=!<>])!(?!=)"), " not "),
    (re.compile(r"\bfn:(\w+)"), r"fn.\1"),
    (re.compile(r"\bnull\b"), "None"),
    (re.compile(r"\btrue\b"), "True"),
    (re.compile(r"\bfalse\b"), "False"),
    (re.compile(r"\beq\b"), "=="),
    (re.compile(r"\bne\b"), "!="),
]

_STRING_SPLIT = re.compile(r"('(?:[^'\\]|\\.)*'|\"(?:[^\"\\]|\\.)*\")")


def _normalise(expr: str) -> str:
    """EL → Python normalisation, applied *outside* string literals only
    (so ``value.flag == 'true'`` keeps its literal intact)."""
    expr = expr.strip()
    # strip full-expression wrappers: {{ expr }} / ${ expr }
    for open_, close in (("{{", "}}"), ("${", "}")):
        if expr.startswith(open_) and expr.endswith(close):
            inner = expr[len(open_) : -len(close)]
            # only unwrap when the braces actually pair around the whole body
            if open_ == "${" and "{" in inner:
                break
            expr = inner.strip()
    parts = _STRING_SPLIT.split(expr)
    for i in range(0, len(parts), 2):  # even indices are outside strings
        for pat, repl in _EL_NORMALISE:
            parts[i] = pat.sub(repl, parts[i])
    return "".join(parts).strip()


def _check(node: ast.AST) -> None:
    for child in ast.walk(node):
        if not isinstance(child, _ALLOWED_NODES):
            raise ExpressionError(
                f"disallowed construct {type(child).__name__} in expression"
            )
        if isinstance(child, ast.Attribute) and child.attr.startswith("_"):
            raise ExpressionError("dunder access is not allowed")
        if isinstance(child, ast.Name) and child.id.startswith("_"):
            raise ExpressionError("underscore names are not allowed")


class _Evaluator(ast.NodeVisitor):
    def __init__(self, names: dict[str, Any]):
        self.names = names

    def run(self, tree: ast.Expression) -> Any:
        return self._eval(tree.body)

    def _eval(self, node: ast.AST) -> Any:
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            if node.id not in self.names:
                return None
            return _wrap(self.names[node.id])
        if isinstance(node, ast.Attribute):
            base = self._eval(node.value)
            if base is None:
                return None
            if isinstance(base, _DotDict):
                return getattr(base, node.attr)
            if isinstance(base, _Fn) or base is _Fn:
                return getattr(base, node.attr)
            if isinstance(base, Mapping):
                return _wrap(base.get(node.attr))
            return None
        if isinstance(node, ast.Subscript):
            base = self._eval(node.value)
            if base is None:
                return None
            idx = self._eval(node.slice)
            try:
                return _wrap(base[idx])
            except (KeyError, IndexError, TypeError):
                return None
        if isinstance(node, ast.Call):
            func = self._eval(node.func)
            if not callable(func):
                raise ExpressionError("call of non-function")
            args = [self._eval(a) for a in node.args]
            return func(*args)
        if isinstance(node, ast.BoolOp):
            if isinstance(node.op, ast.And):
                result: Any = True
                for v in node.values:
                    result = self._eval(v)
                    if not result:
                        return result
                return result
            result = False
            for v in node.values:
                result = self._eval(v)
                if result:
                    return result
            return result
        if isinstance(node, ast.UnaryOp):
            operand = self._eval(node.operand)
            if isinstance(node.op, ast.Not):
                return not operand
            if isinstance(node.op, ast.USub):
                return -operand
            return +operand
        if isinstance(node, ast.BinOp):
            left, right = self._eval(node.left), self._eval(node.right)
            ops = {
                ast.Add: lambda a, b: a + b,
                ast.Sub: lambda a, b: a - b,
                ast.Mult: lambda a, b: a * b,
                ast.Div: lambda a, b: a / b,
                ast.FloorDiv: lambda a, b: a // b,
                ast.Mod: lambda a, b: a % b,
                ast.Pow: lambda a, b: a ** b,
            }
            return ops[type(node.op)](left, right)
        if isinstance(node, ast.Compare):
            left = self._eval(node.left)
            for op, comp in zip(node.ops, node.comparators):
                right = self._eval(comp)
                ok = {
                    ast.Eq: lambda a, b: a == b,
                    ast.NotEq: lambda a, b: a != b,
                    ast.Lt: lambda a, b: a < b,
                    ast.LtE: lambda a, b: a <= b,
                    ast.Gt: lambda a, b: a > b,
                    ast.GtE: lambda a, b: a >= b,
                    ast.In: lambda a, b: a in b if b is not None else False,
                    ast.NotIn: lambda a, b: a not in b if b is not None else True,
                    ast.Is: lambda a, b: a is b,
                    ast.IsNot: lambda a, b: a is not b,
                }[type(op)](left, right)
                if not ok:
                    return False
                left = right
            return True
        if isinstance(node, ast.IfExp):
            return (
                self._eval(node.body) if self._eval(node.test) else self._eval(node.orelse)
            )
        if isinstance(node, ast.List):
            return [self._eval(e) for e in node.elts]
        if isinstance(node, ast.Tuple):
            return tuple(self._eval(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return {
                self._eval(k): self._eval(v)
                for k, v in zip(node.keys, node.values)
            }
        if isinstance(node, ast.Slice):
            return slice(
                self._eval(node.lower) if node.lower else None,
                self._eval(node.upper) if node.upper else None,
                self._eval(node.step) if node.step else None,
            )
        raise ExpressionError(f"unsupported node {type(node).__name__}")


def context_names(record: MutableRecord | None, extra: Mapping[str, Any] | None = None) -> dict[str, Any]:
    names: dict[str, Any] = {"fn": _Fn()}
    if record is not None:
        names.update(
            value=record.value,
            key=record.key,
            properties=record.properties,
            origin=record.origin,
            timestamp=record.timestamp,
        )
    if extra:
        names.update(extra)
    return names


def evaluate(
    expression: str,
    record: MutableRecord | None = None,
    extra: Mapping[str, Any] | None = None,
) -> Any:
    """Evaluate an expression against a record context."""
    src = _normalise(expression)
    if not src:
        return None
    try:
        tree = ast.parse(src, mode="eval")
    except SyntaxError as e:
        raise ExpressionError(f"bad expression {expression!r}: {e}") from e
    _check(tree)
    return _Evaluator(context_names(record, extra)).run(tree)


_ACCESSOR_MISS = object()


def evaluate_accessor(
    accessor: str, record: MutableRecord, extra: Mapping[str, Any] | None = None
) -> Any:
    """Fast path for plain dotted accessors; falls back to full evaluation."""
    if re.fullmatch(r"[A-Za-z_][\w]*(\.[\w]+)*", accessor or ""):
        if accessor.split(".", 1)[0] in ("value", "key", "properties", "origin", "timestamp"):
            return record.get_field(accessor)
    # Dotted paths whose segments contain hyphens (gateway headers like
    # properties.langstream-client-session-id) are valid field accessors but
    # would parse as subtraction in the EL; resolve as an accessor first and
    # only hand genuine misses to the evaluator.
    if re.fullmatch(r"[A-Za-z_][\w]*(\.[\w][\w-]*)+", accessor or ""):
        if accessor.split(".", 1)[0] in ("value", "key", "properties", "origin", "timestamp"):
            hit = record.get_field(accessor, _ACCESSOR_MISS)
            if hit is not _ACCESSOR_MISS:
                return hit
    return evaluate(accessor, record, extra)


# ---------------------------------------------------------------------------
# Mustache-style templates
# ---------------------------------------------------------------------------

_TAG = re.compile(r"\{\{\s*([#^/!]?)\s*([^}]*?)\s*\}\}")


def _lookup(path: str, stack: list[Any]) -> Any:
    parts = path.split(".")
    for frame in reversed(stack):
        cur = frame
        found = True
        for i, p in enumerate(parts):
            if isinstance(cur, Mapping) and p in cur:
                cur = cur[p]
            elif p == "." and i == 0:
                break
            else:
                found = False
                break
        if found:
            return cur
    return None


def render_template(
    template: str,
    record: MutableRecord | None = None,
    extra: Mapping[str, Any] | None = None,
) -> str:
    """Render a Mustache template against the record context.

    Supports ``{{ path }}``, sections ``{{# path}}…{{/path}}`` (list
    iteration, truthy gating), inverted ``{{^ path}}``, comments ``{{! }}``,
    and ``{{.}}`` for the current list item.
    """
    root = context_names(record, extra)
    del root["fn"]
    tokens = _tokenise(template)
    out: list[str] = []
    _render_tokens(tokens, 0, len(tokens), [root], out)
    return "".join(out)


def _tokenise(template: str) -> list[tuple[str, str]]:
    tokens: list[tuple[str, str]] = []
    pos = 0
    for m in _TAG.finditer(template):
        if m.start() > pos:
            tokens.append(("text", template[pos : m.start()]))
        sigil, path = m.group(1), m.group(2)
        kind = {"#": "open", "^": "inv", "/": "close", "!": "comment"}.get(sigil, "var")
        tokens.append((kind, path))
        pos = m.end()
    if pos < len(template):
        tokens.append(("text", template[pos:]))
    return tokens


def _find_close(tokens: list[tuple[str, str]], start: int, path: str) -> int:
    depth = 0
    for i in range(start, len(tokens)):
        kind, p = tokens[i]
        if kind in ("open", "inv"):
            depth += 1
        elif kind == "close":
            if depth == 0 and (p == path or not p):
                return i
            depth -= 1
    raise ExpressionError(f"unclosed section {{#{path}}}")


def _render_tokens(
    tokens: list[tuple[str, str]],
    start: int,
    end: int,
    stack: list[Any],
    out: list[str],
) -> None:
    i = start
    while i < end:
        kind, payload = tokens[i]
        if kind == "text":
            out.append(payload)
        elif kind == "comment":
            pass
        elif kind == "var":
            if payload == ".":
                v = stack[-1].get(".", stack[-1]) if isinstance(stack[-1], Mapping) else stack[-1]
            else:
                v = _lookup(payload, stack)
            if v is not None:
                out.append(v if isinstance(v, str) else json.dumps(v) if isinstance(v, (dict, list)) else str(v))
        elif kind in ("open", "inv"):
            close = _find_close(tokens, i + 1, payload)
            v = _lookup(payload, stack)
            if kind == "open":
                if isinstance(v, list):
                    for item in v:
                        frame = item if isinstance(item, Mapping) else {".": item}
                        _render_tokens(tokens, i + 1, close, stack + [frame], out)
                elif v:
                    frame = v if isinstance(v, Mapping) else {".": v}
                    _render_tokens(tokens, i + 1, close, stack + [frame], out)
            else:
                if not v:
                    _render_tokens(tokens, i + 1, close, stack, out)
            i = close
        i += 1
