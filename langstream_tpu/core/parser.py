"""YAML → Application parser.

Parity: ``ModelBuilder`` (``langstream-core/.../parser/ModelBuilder.java:370``):
an application directory holds

- one or more *pipeline files* (``*.yaml`` with top-level ``topics:`` /
  ``pipeline:`` / ``assets:`` / ``errors:`` / ``module:``),
- ``configuration.yaml`` (``configuration: {resources: [...],
  dependencies: [...]}``),
- ``gateways.yaml`` (``gateways: [...]``),

plus, supplied separately (as the CLI does): ``instance.yaml``
(``instance: {streamingCluster, computeCluster, globals}``,
``ModelBuilder.java:837``) and ``secrets.yaml`` (``secrets: [{id,name,data}]``,
``ModelBuilder.java:812``).
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Any

import yaml

from langstream_tpu.api.application import (
    AgentConfiguration,
    Application,
    AssetDefinition,
    ComputeCluster,
    ErrorsSpec,
    Gateway,
    Instance,
    Module,
    Pipeline,
    Resource,
    ResourcesSpec,
    Secret,
    Secrets,
    StreamingCluster,
    TopicDefinition,
    DEFAULT_MODULE,
)

_RESERVED_FILES = {"configuration.yaml", "gateways.yaml", "secrets.yaml", "instance.yaml"}

_ID_SANITISE = re.compile(r"[^a-z0-9-]")


def _sanitise_id(name: str) -> str:
    return _ID_SANITISE.sub("-", name.lower()).strip("-")


class ApplicationParseError(ValueError):
    pass


class ModelBuilder:
    """Incremental builder: feed files, then :meth:`build`."""

    def __init__(self) -> None:
        self.application = Application()

    # ---- per-file entry points ------------------------------------------

    def add_pipeline_file(self, name: str, content: str) -> None:
        data = yaml.safe_load(content)
        if data is None:
            return
        if not isinstance(data, dict):
            raise ApplicationParseError(f"{name}: expected a mapping at top level")
        module = self.application.get_module(data.get("module", DEFAULT_MODULE))

        for topic_data in data.get("topics") or []:
            topic = TopicDefinition.from_dict(topic_data)
            existing = module.topics.get(topic.name)
            if existing is not None and existing.creation_mode != topic.creation_mode:
                raise ApplicationParseError(
                    f"{name}: topic {topic.name!r} redeclared with a different "
                    f"creation-mode"
                )
            module.topics.setdefault(topic.name, topic)

        for asset_data in data.get("assets") or []:
            module.assets.append(
                AssetDefinition(
                    id=asset_data.get("id") or _sanitise_id(asset_data.get("name", "asset")),
                    name=asset_data.get("name", ""),
                    asset_type=asset_data.get("asset-type", ""),
                    creation_mode=asset_data.get("creation-mode", "none"),
                    deletion_mode=asset_data.get("deletion-mode", "none"),
                    config=asset_data.get("config") or {},
                    events_topic=asset_data.get("events-topic"),
                )
            )

        steps = data.get("pipeline")
        if steps is None:
            return
        pipeline_id = data.get("id") or Path(name).stem
        pipeline = Pipeline(
            id=pipeline_id,
            name=data.get("name") or pipeline_id,
            resources=ResourcesSpec.from_dict(data.get("resources")),
            errors=ErrorsSpec.from_dict(data.get("errors")),
        )
        seen_ids: set[str] = set()
        for idx, step in enumerate(steps):
            if "type" not in step:
                raise ApplicationParseError(
                    f"{name}: pipeline step #{idx} has no 'type'"
                )
            agent_id = step.get("id") or _sanitise_id(
                step.get("name") or f"{step['type']}-{idx}"
            )
            if agent_id in seen_ids:
                agent_id = f"{agent_id}-{idx}"
            seen_ids.add(agent_id)
            agent = AgentConfiguration(
                id=agent_id,
                name=step.get("name", agent_id),
                type=step["type"],
                input=step.get("input"),
                output=step.get("output"),
                configuration=step.get("configuration") or {},
                resources=ResourcesSpec.from_dict(
                    step.get("resources") or data.get("resources")
                ),
                errors=ErrorsSpec.from_dict(step.get("errors")),
            )
            pipeline.agents.append(agent)
        if pipeline.id in module.pipelines:
            raise ApplicationParseError(f"duplicate pipeline id {pipeline.id!r}")
        module.pipelines[pipeline.id] = pipeline

    def add_configuration_file(self, content: str) -> None:
        data = yaml.safe_load(content) or {}
        configuration = data.get("configuration") or {}
        for res in configuration.get("resources") or []:
            resource = Resource(
                id=res.get("id") or _sanitise_id(res.get("name") or res.get("type")),
                name=res.get("name", ""),
                type=res.get("type", ""),
                configuration=res.get("configuration") or {},
            )
            self.application.resources[resource.id] = resource
        self.application.dependencies.extend(configuration.get("dependencies") or [])

    def add_gateways_file(self, content: str) -> None:
        data = yaml.safe_load(content) or {}
        for gw in data.get("gateways") or []:
            self.application.gateways.append(Gateway.from_dict(gw))

    def add_instance(self, content: str) -> None:
        data = yaml.safe_load(content) or {}
        instance = data.get("instance") or {}
        streaming = instance.get("streamingCluster") or {}
        compute = instance.get("computeCluster") or {}
        self.application.instance = Instance(
            streaming_cluster=StreamingCluster(
                type=streaming.get("type", "memory"),
                configuration=streaming.get("configuration") or {},
            ),
            compute_cluster=ComputeCluster(
                type=compute.get("type", "local"),
                configuration=compute.get("configuration") or {},
            ),
            globals_=instance.get("globals") or {},
        )

    def add_secrets(self, content: str) -> None:
        data = yaml.safe_load(content) or {}
        secrets: dict[str, Secret] = {}
        for s in data.get("secrets") or []:
            secret = Secret(
                id=s.get("id") or _sanitise_id(s.get("name", "")),
                name=s.get("name", ""),
                data=s.get("data") or {},
            )
            secrets[secret.id] = secret
        self.application.secrets = Secrets(secrets=secrets)

    # ---- named-file dispatch --------------------------------------------

    def add_named_file(self, name: str, content: str) -> None:
        """Route one application file by its reserved name (the single
        dispatch point shared by the directory and in-memory entry points)."""
        if name == "configuration.yaml":
            self.add_configuration_file(content)
        elif name == "gateways.yaml":
            self.add_gateways_file(content)
        elif name == "secrets.yaml":
            self.add_secrets(content)
        elif name == "instance.yaml":
            self.add_instance(content)
        elif name.endswith((".yaml", ".yml")):
            self.add_pipeline_file(name, content)

    def add_application_directory(self, directory: Path | str) -> None:
        directory = Path(directory)
        if not directory.is_dir():
            raise ApplicationParseError(f"not a directory: {directory}")
        self.application.directory = str(directory)
        for path in sorted(directory.glob("*.yaml")) + sorted(directory.glob("*.yml")):
            self.add_named_file(path.name, path.read_text())

    def build(self) -> Application:
        return self.application


def build_application_from_files(
    files: dict[str, str],
    instance: str | None = None,
    secrets: str | None = None,
) -> Application:
    """Parse from an in-memory filename→content map (the shape stored by the
    control plane and shipped to in-cluster setup/deployer Jobs)."""
    builder = ModelBuilder()
    for name in sorted(files):
        builder.add_named_file(name, files[name])
    if instance is not None:
        builder.add_instance(instance)
    if secrets is not None:
        builder.add_secrets(secrets)
    return builder.build()


def build_application_from_directory(
    directory: Path | str,
    instance: str | Path | None = None,
    secrets: str | Path | None = None,
) -> Application:
    """One-shot parse of an application directory plus optional instance and
    secrets files (paths or YAML strings)."""
    builder = ModelBuilder()
    builder.add_application_directory(directory)

    def _content(source: str | Path) -> str:
        p = Path(source) if not isinstance(source, Path) else source
        try:
            if p.exists():
                return p.read_text()
        except OSError:
            pass
        return str(source)

    if instance is not None:
        builder.add_instance(_content(instance))
    if secrets is not None:
        builder.add_secrets(_content(secrets))
    return builder.build()
