"""``${secrets.x.y}`` / ``${globals.x}`` placeholder resolution.

Parity: ``ApplicationPlaceholderResolver``
(``langstream-core/.../common/ApplicationPlaceholderResolver.java:59``) —
resolves placeholders across the whole application model after parsing, from
the secrets file and instance globals. Unresolvable placeholders raise, except
inside agent ``configuration`` blocks where unknown roots are left verbatim
(they may be runtime expressions).
"""

from __future__ import annotations

import re
from typing import Any

from langstream_tpu.api.application import Application

_PLACEHOLDER = re.compile(r"\$\{\s*([a-zA-Z0-9_.-]+)\s*\}")


class PlaceholderError(ValueError):
    pass


def _build_context(application: Application) -> dict[str, Any]:
    secrets: dict[str, Any] = {}
    for sid, secret in application.secrets.secrets.items():
        secrets[sid] = secret.data
    return {
        "secrets": secrets,
        "globals": application.instance.globals_,
        "cluster": {
            "streaming": {
                "type": application.instance.streaming_cluster.type,
                **application.instance.streaming_cluster.configuration,
            },
            "compute": {
                "type": application.instance.compute_cluster.type,
            },
        },
    }


def _lookup(path: str, context: dict[str, Any]) -> Any:
    parts = path.split(".")
    cur: Any = context
    for p in parts:
        if isinstance(cur, dict) and p in cur:
            cur = cur[p]
        else:
            raise PlaceholderError(f"cannot resolve placeholder ${{{path}}}")
    return cur


def resolve_value(value: Any, context: dict[str, Any], strict: bool = True) -> Any:
    if isinstance(value, str):
        full = _PLACEHOLDER.fullmatch(value.strip())
        if full:
            # whole-string placeholder: preserve the resolved type
            try:
                return resolve_value(_lookup(full.group(1), context), context, strict)
            except PlaceholderError:
                if strict and full.group(1).split(".")[0] in context:
                    raise
                return value

        def _sub(m: re.Match) -> str:
            try:
                v = _lookup(m.group(1), context)
                return "" if v is None else str(v)
            except PlaceholderError:
                if strict and m.group(1).split(".")[0] in context:
                    raise
                return m.group(0)

        return _PLACEHOLDER.sub(_sub, value)
    if isinstance(value, dict):
        return {k: resolve_value(v, context, strict) for k, v in value.items()}
    if isinstance(value, list):
        return [resolve_value(v, context, strict) for v in value]
    return value


def resolve_placeholders(application: Application) -> Application:
    """Resolve placeholders in-place across resources, agent configurations,
    gateways, and instance configuration. Returns the same application."""
    context = _build_context(application)

    # instance globals may themselves reference secrets
    application.instance.globals_ = resolve_value(
        application.instance.globals_, context
    )
    context = _build_context(application)

    application.instance.streaming_cluster.configuration = resolve_value(
        application.instance.streaming_cluster.configuration, context
    )
    for resource in application.resources.values():
        resource.configuration = resolve_value(resource.configuration, context)
    for module in application.modules.values():
        for asset in module.assets:
            asset.config = resolve_value(asset.config, context)
        for pipeline in module.pipelines.values():
            for agent in pipeline.agents:
                agent.configuration = resolve_value(agent.configuration, context)
    for gateway in application.gateways:
        if gateway.authentication:
            gateway.authentication = resolve_value(gateway.authentication, context)
        for hm in gateway.produce_headers + gateway.consume_filters:
            if isinstance(hm.literal_value, str):
                hm.literal_value = resolve_value(hm.literal_value, context)
    return application
