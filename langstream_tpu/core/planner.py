"""Planner: Application → ExecutionPlan.

Parity: ``BasicClusterRuntime.buildExecutionPlan`` →
``detectTopics`` / ``detectAssets`` / ``detectAgents``
(``langstream-core/.../common/BasicClusterRuntime.java:50-147``) plus the
agent-fusion optimisation (``ComposableAgentExecutionPlanOptimiser.java:34``,
``BasicClusterRuntime.java:233-249``): consecutive *composable* agents with
equal resource specs and no explicit topic between them are merged into one
composite node, removing a broker round-trip. Stages that are not fused are
joined by implicit topics.
"""

from __future__ import annotations

from dataclasses import dataclass

from langstream_tpu.api.agent import ComponentType
from langstream_tpu.api.application import (
    AgentConfiguration,
    Application,
    ErrorsSpec,
    Pipeline,
    TopicDefinition,
)
from langstream_tpu.api.execution_plan import AgentNode, Connection, ExecutionPlan


@dataclass
class AgentTypeMetadata:
    component_type: ComponentType
    composable: bool = True


# Planner-side metadata per agent ``type:`` string. The agents package
# extends this on import (parity: the per-agent planner providers under
# ``langstream-k8s-runtime/.../k8s/agents/*.java``).
AGENT_TYPE_METADATA: dict[str, AgentTypeMetadata] = {}


def register_agent_type(
    agent_type: str,
    component_type: ComponentType,
    composable: bool = True,
) -> None:
    AGENT_TYPE_METADATA[agent_type] = AgentTypeMetadata(component_type, composable)


def get_metadata(agent_type: str) -> AgentTypeMetadata:
    # Ensure built-in agents had a chance to register their metadata.
    import langstream_tpu.agents  # noqa: F401

    if agent_type in AGENT_TYPE_METADATA:
        return AGENT_TYPE_METADATA[agent_type]
    # Unknown types (e.g. custom python) default to composable processors.
    return AgentTypeMetadata(ComponentType.PROCESSOR, True)


class PlanningError(ValueError):
    pass


# Agent types the framework deliberately does not carry, with the reason and
# the supported alternative — using one fails AT PLANNING TIME with a clear
# message instead of at pod start with a confusing import error. (r3 verdict
# missing #2: camel had no counterpart and no planner-visible descope.)
DESCOPED_AGENT_TYPES: dict[str, str] = {
    "camel-source": (
        "camel-source embeds Apache Camel's JVM connector ecosystem "
        "(reference: langstream-agent-camel/.../CamelSource.java) and has no "
        "Python counterpart here (deliberate descope, see README). Use the "
        "Connect-style 'source' bridge agent, the 'webcrawler'/'s3-source'/"
        "'azure-blob-storage-source' sources, 'http-request', or a custom "
        "'python-source'."
    ),
}


class Planner:
    def __init__(self, application_id: str, application: Application):
        self.application_id = application_id
        self.application = application

    def build(self) -> ExecutionPlan:
        plan = ExecutionPlan(
            application_id=self.application_id, application=self.application
        )
        self._detect_topics(plan)
        self._detect_assets(plan)
        self._detect_agents(plan)
        return plan

    def _detect_topics(self, plan: ExecutionPlan) -> None:
        for module in self.application.modules.values():
            for topic in module.topics.values():
                if topic.name in plan.topics:
                    continue
                plan.topics[topic.name] = topic

    def _detect_assets(self, plan: ExecutionPlan) -> None:
        for module in self.application.modules.values():
            plan.assets.extend(module.assets)

    def _detect_agents(self, plan: ExecutionPlan) -> None:
        for module in self.application.modules.values():
            for pipeline in module.pipelines.values():
                self._plan_pipeline(plan, pipeline)

    def _plan_pipeline(self, plan: ExecutionPlan, pipeline: Pipeline) -> None:
        agents = pipeline.agents
        if not agents:
            return

        for agent in agents:
            if agent.type in DESCOPED_AGENT_TYPES:
                raise PlanningError(
                    f"agent {agent.id!r} in pipeline {pipeline.id!r}: "
                    f"{DESCOPED_AGENT_TYPES[agent.type]}"
                )

        # 1. group consecutive fusable agents
        groups: list[list[AgentConfiguration]] = []
        for agent in agents:
            if groups and self._can_fuse(groups[-1][-1], agent):
                groups[-1].append(agent)
            else:
                groups.append([agent])

        # 2. wire groups with topics
        previous_output: str | None = None
        for gi, group in enumerate(groups):
            head, tail = group[0], group[-1]
            head_meta = get_metadata(head.type)
            tail_meta = get_metadata(tail.type)

            # input connection
            input_topic = head.input or previous_output
            if input_topic is None and head_meta.component_type != ComponentType.SOURCE \
                    and head_meta.component_type != ComponentType.SERVICE:
                raise PlanningError(
                    f"agent {head.id!r} in pipeline {pipeline.id!r} has no input "
                    f"topic and is not a source"
                )
            if input_topic is not None and input_topic not in plan.topics:
                raise PlanningError(
                    f"agent {head.id!r} references undeclared topic {input_topic!r}"
                )

            # output connection
            is_last = gi == len(groups) - 1
            output_topic = tail.output
            if output_topic is None and not is_last:
                nxt = groups[gi + 1][0]
                if nxt.input is None:
                    # implicit topic between this group and the next
                    output_topic = self._implicit_topic(plan, pipeline, tail)
                    nxt.input = output_topic
            if output_topic is not None and output_topic not in plan.topics:
                raise PlanningError(
                    f"agent {tail.id!r} references undeclared topic {output_topic!r}"
                )

            errors = self._effective_errors(pipeline, head)
            node = AgentNode(
                id=group[0].id,
                agent_type="composite" if len(group) > 1 else head.type,
                component_type=self._composite_component_type(group).value,
                input=(
                    Connection(
                        input_topic,
                        deadletter_enabled=errors.on_failure == ErrorsSpec.DEAD_LETTER,
                    )
                    if input_topic
                    else None
                ),
                output=Connection(output_topic) if output_topic else None,
                agents=list(group),
                resources=head.resources,
                errors=errors,
                configuration=dict(head.configuration) if len(group) == 1 else {},
            )
            if node.id in plan.agents:
                raise PlanningError(f"duplicate agent id {node.id!r}")
            plan.agents[node.id] = node
            previous_output = output_topic
            if tail_meta.component_type == ComponentType.SINK:
                previous_output = None

    def _can_fuse(self, prev: AgentConfiguration, nxt: AgentConfiguration) -> bool:
        if prev.output is not None or nxt.input is not None:
            return False
        prev_meta, nxt_meta = get_metadata(prev.type), get_metadata(nxt.type)
        if not (prev_meta.composable and nxt_meta.composable):
            return False
        # a source may fuse with following processors; processors fuse with
        # processors and a trailing sink (parity: composite agent rules)
        ok_prev = prev_meta.component_type in (
            ComponentType.SOURCE,
            ComponentType.PROCESSOR,
        )
        ok_next = nxt_meta.component_type in (
            ComponentType.PROCESSOR,
            ComponentType.SINK,
        )
        if not (ok_prev and ok_next):
            return False
        # equal scaling requirements only (BasicClusterRuntime.java:233-249)
        if (prev.resources.parallelism, prev.resources.size) != (
            nxt.resources.parallelism,
            nxt.resources.size,
        ):
            return False
        if prev.resources.device_mesh != nxt.resources.device_mesh:
            return False
        # per-agent error policies survive fusion in our runtime, so they do
        # not block it.
        return True

    def _composite_component_type(self, group: list[AgentConfiguration]) -> ComponentType:
        first = get_metadata(group[0].type).component_type
        last = get_metadata(group[-1].type).component_type
        if first == ComponentType.SOURCE:
            return ComponentType.SOURCE
        if last == ComponentType.SINK:
            return ComponentType.SINK
        return first if len(group) == 1 else ComponentType.PROCESSOR

    def _implicit_topic(
        self, plan: ExecutionPlan, pipeline: Pipeline, after: AgentConfiguration
    ) -> str:
        name = f"{self.application_id}-{pipeline.id}-{after.id}-output"
        if name not in plan.topics:
            plan.topics[name] = TopicDefinition(
                name=name,
                creation_mode=TopicDefinition.CREATE_IF_NOT_EXISTS,
                deletion_mode="delete",
                implicit=True,
            )
        return name

    def _effective_errors(
        self, pipeline: Pipeline, agent: AgentConfiguration
    ) -> ErrorsSpec:
        if agent.errors is not None:
            return agent.errors.with_defaults(pipeline.errors)
        return pipeline.errors or ErrorsSpec()


def build_execution_plan(application_id: str, application: Application) -> ExecutionPlan:
    return Planner(application_id, application).build()
