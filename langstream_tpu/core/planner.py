"""Planner: Application → ExecutionPlan.

Parity: ``BasicClusterRuntime.buildExecutionPlan`` →
``detectTopics`` / ``detectAssets`` / ``detectAgents``
(``langstream-core/.../common/BasicClusterRuntime.java:50-147``) plus the
agent-fusion optimisation (``ComposableAgentExecutionPlanOptimiser.java:34``,
``BasicClusterRuntime.java:233-249``): consecutive *composable* agents with
equal resource specs and no explicit topic between them are merged into one
composite node, removing a broker round-trip. Stages that are not fused are
joined by implicit topics.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from langstream_tpu.api.agent import ComponentType
from langstream_tpu.api.application import (
    AgentConfiguration,
    Application,
    ErrorsSpec,
    Pipeline,
    TopicDefinition,
)
from langstream_tpu.api.execution_plan import AgentNode, Connection, ExecutionPlan


@dataclass
class AgentTypeMetadata:
    component_type: ComponentType
    composable: bool = True


# Planner-side metadata per agent ``type:`` string. The agents package
# extends this on import (parity: the per-agent planner providers under
# ``langstream-k8s-runtime/.../k8s/agents/*.java``).
AGENT_TYPE_METADATA: dict[str, AgentTypeMetadata] = {}


def register_agent_type(
    agent_type: str,
    component_type: ComponentType,
    composable: bool = True,
) -> None:
    AGENT_TYPE_METADATA[agent_type] = AgentTypeMetadata(component_type, composable)


def get_metadata(agent_type: str) -> AgentTypeMetadata:
    # Ensure built-in agents had a chance to register their metadata.
    import langstream_tpu.agents  # noqa: F401

    if agent_type in AGENT_TYPE_METADATA:
        return AGENT_TYPE_METADATA[agent_type]
    # Unknown types (e.g. custom python) default to composable processors.
    return AgentTypeMetadata(ComponentType.PROCESSOR, True)


class PlanningError(ValueError):
    pass


# Agent types the framework deliberately does not carry, with the reason and
# the supported alternative — using one fails AT PLANNING TIME with a clear
# message instead of at pod start with a confusing import error. (r3 verdict
# missing #2. camel-source has since graduated from this table to a native
# timer:/file: subset — agents/camel.py — whose unsupported schemes still
# fail at planning via its registered config validator below.)
DESCOPED_AGENT_TYPES: dict[str, str] = {}

# Per-type configuration validators, run at planning time (parity: the
# reference validates agent configs in the planner-side agent providers,
# langstream-k8s-runtime/.../k8s/agents/*.java, not in the pod). A validator
# raises ValueError; the planner wraps it with the agent/pipeline context.
AGENT_CONFIG_VALIDATORS: dict[str, Callable[[dict], None]] = {}


def register_config_validator(agent_type: str, validator: Callable[[dict], None]):
    AGENT_CONFIG_VALIDATORS[agent_type] = validator


class Planner:
    def __init__(self, application_id: str, application: Application):
        self.application_id = application_id
        self.application = application

    def build(self) -> ExecutionPlan:
        plan = ExecutionPlan(
            application_id=self.application_id, application=self.application
        )
        self._detect_topics(plan)
        self._detect_assets(plan)
        self._detect_agents(plan)
        return plan

    def _detect_topics(self, plan: ExecutionPlan) -> None:
        for module in self.application.modules.values():
            for topic in module.topics.values():
                if topic.name in plan.topics:
                    continue
                plan.topics[topic.name] = topic

    def _detect_assets(self, plan: ExecutionPlan) -> None:
        for module in self.application.modules.values():
            plan.assets.extend(module.assets)

    def _detect_agents(self, plan: ExecutionPlan) -> None:
        for module in self.application.modules.values():
            for pipeline in module.pipelines.values():
                self._plan_pipeline(plan, pipeline)

    def _plan_pipeline(self, plan: ExecutionPlan, pipeline: Pipeline) -> None:
        agents = pipeline.agents
        if not agents:
            return

        for agent in agents:
            if agent.type in DESCOPED_AGENT_TYPES:
                raise PlanningError(
                    f"agent {agent.id!r} in pipeline {pipeline.id!r}: "
                    f"{DESCOPED_AGENT_TYPES[agent.type]}"
                )
            validator = AGENT_CONFIG_VALIDATORS.get(agent.type)
            if validator is not None:
                try:
                    validator(agent.configuration)
                except PlanningError:
                    raise
                except Exception as e:
                    # any validator crash IS a planning failure — wrap it so
                    # the user always gets the agent/pipeline context instead
                    # of a bare traceback (e.g. a string where a map belongs
                    # raising AttributeError inside the validator)
                    detail = str(e) if isinstance(e, ValueError) else f"{type(e).__name__}: {e}"
                    raise PlanningError(
                        f"agent {agent.id!r} in pipeline {pipeline.id!r}: {detail}"
                    ) from None

        # 1. group consecutive fusable agents
        groups: list[list[AgentConfiguration]] = []
        for agent in agents:
            if groups and self._can_fuse(groups[-1][-1], agent):
                groups[-1].append(agent)
            else:
                groups.append([agent])

        # 2. wire groups with topics
        previous_output: str | None = None
        for gi, group in enumerate(groups):
            head, tail = group[0], group[-1]
            head_meta = get_metadata(head.type)
            tail_meta = get_metadata(tail.type)

            # input connection
            input_topic = head.input or previous_output
            if input_topic is None and head_meta.component_type != ComponentType.SOURCE \
                    and head_meta.component_type != ComponentType.SERVICE:
                raise PlanningError(
                    f"agent {head.id!r} in pipeline {pipeline.id!r} has no input "
                    f"topic and is not a source"
                )
            if input_topic is not None and input_topic not in plan.topics:
                raise PlanningError(
                    f"agent {head.id!r} references undeclared topic {input_topic!r}"
                )

            # output connection
            is_last = gi == len(groups) - 1
            output_topic = tail.output
            if output_topic is None and not is_last:
                nxt = groups[gi + 1][0]
                if nxt.input is None:
                    # implicit topic between this group and the next
                    output_topic = self._implicit_topic(plan, pipeline, tail)
                    nxt.input = output_topic
            if output_topic is not None and output_topic not in plan.topics:
                raise PlanningError(
                    f"agent {tail.id!r} references undeclared topic {output_topic!r}"
                )

            errors = self._effective_errors(pipeline, head)
            node = AgentNode(
                id=group[0].id,
                agent_type="composite" if len(group) > 1 else head.type,
                component_type=self._composite_component_type(group).value,
                input=(
                    Connection(
                        input_topic,
                        deadletter_enabled=errors.on_failure == ErrorsSpec.DEAD_LETTER,
                    )
                    if input_topic
                    else None
                ),
                output=Connection(output_topic) if output_topic else None,
                agents=list(group),
                resources=head.resources,
                errors=errors,
                configuration=dict(head.configuration) if len(group) == 1 else {},
            )
            if node.id in plan.agents:
                raise PlanningError(f"duplicate agent id {node.id!r}")
            plan.agents[node.id] = node
            previous_output = output_topic
            if tail_meta.component_type == ComponentType.SINK:
                previous_output = None

    def _can_fuse(self, prev: AgentConfiguration, nxt: AgentConfiguration) -> bool:
        if prev.output is not None or nxt.input is not None:
            return False
        prev_meta, nxt_meta = get_metadata(prev.type), get_metadata(nxt.type)
        if not (prev_meta.composable and nxt_meta.composable):
            return False
        # a source may fuse with following processors; processors fuse with
        # processors and a trailing sink (parity: composite agent rules)
        ok_prev = prev_meta.component_type in (
            ComponentType.SOURCE,
            ComponentType.PROCESSOR,
        )
        ok_next = nxt_meta.component_type in (
            ComponentType.PROCESSOR,
            ComponentType.SINK,
        )
        if not (ok_prev and ok_next):
            return False
        # equal scaling requirements only (BasicClusterRuntime.java:233-249)
        if (prev.resources.parallelism, prev.resources.size) != (
            nxt.resources.parallelism,
            nxt.resources.size,
        ):
            return False
        if prev.resources.device_mesh != nxt.resources.device_mesh:
            return False
        # per-agent error policies survive fusion in our runtime, so they do
        # not block it.
        return True

    def _composite_component_type(self, group: list[AgentConfiguration]) -> ComponentType:
        first = get_metadata(group[0].type).component_type
        last = get_metadata(group[-1].type).component_type
        if first == ComponentType.SOURCE:
            return ComponentType.SOURCE
        if last == ComponentType.SINK:
            return ComponentType.SINK
        return first if len(group) == 1 else ComponentType.PROCESSOR

    def _implicit_topic(
        self, plan: ExecutionPlan, pipeline: Pipeline, after: AgentConfiguration
    ) -> str:
        name = f"{self.application_id}-{pipeline.id}-{after.id}-output"
        if name not in plan.topics:
            plan.topics[name] = TopicDefinition(
                name=name,
                creation_mode=TopicDefinition.CREATE_IF_NOT_EXISTS,
                deletion_mode="delete",
                implicit=True,
            )
        return name

    def _effective_errors(
        self, pipeline: Pipeline, agent: AgentConfiguration
    ) -> ErrorsSpec:
        if agent.errors is not None:
            return agent.errors.with_defaults(pipeline.errors)
        return pipeline.errors or ErrorsSpec()


def build_execution_plan(application_id: str, application: Application) -> ExecutionPlan:
    return Planner(application_id, application).build()
