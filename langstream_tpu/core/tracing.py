"""End-to-end record tracing: propagated context, spans, ring buffer.

The Dapper-style counterpart of the per-agent Prometheus counters: a
record picks up a ``langstream-trace`` header at the first hop (gateway
produce, or the runner when a source-originated record has none) and every
layer it crosses — gateway, agent hops, composite stages, the serving
engine — contributes spans sharing the header's ``trace_id``. With it, a
3 s client TTFT decomposes into named per-hop spans instead of one opaque
number (see ``docs/OBSERVABILITY.md``).

Design constraints (this module is on the record hot path):

- **zero dependencies** — stdlib only, importable from every layer;
- **always-on-cheap** — a span is one small object and one deque append;
  ids come from ``os.urandom``; durations from ``time.monotonic()``
  (wall clock is for display anchoring only, never measurement);
- **never raises** — span finishing and JSONL export swallow their own
  failures; tracing must not take down serving;
- **bounded** — finished spans land in a process-global ring buffer
  (``LS_TPU_TRACE_BUFFER`` entries, default 2048) served by the pod's
  ``/traces`` endpoints; optional durable export appends JSONL lines to
  ``LS_TPU_TRACE_LOG``.

Header format (W3C ``traceparent``-compatible):
``00-<32 hex trace_id>-<16 hex span_id>-01``.

Context propagates two ways:

- **on the record** — the ``langstream-trace`` header rides the record
  through brokers exactly like any other string header (the kafka lanes
  serialize headers reversibly; the memory broker passes them through);
- **ambiently** — a :data:`contextvars.ContextVar` set by the runtime
  around per-record processing, so deep callees (the serving engine's
  ``generate``) can parent their spans without any signature plumbing.
  ``asyncio`` tasks snapshot the context at creation, which is exactly
  the per-record task boundary the runtime uses.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any

log = logging.getLogger(__name__)

#: the record header carrying the trace context across hops (preserved by
#: every broker runtime the way ``OFFSET_HEADER`` is transport-local)
TRACE_HEADER = "langstream-trace"

_VERSION = "00"
_FLAGS = "01"  # sampled


def _hex_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def fresh_trace_id() -> str:
    """A new 32-hex trace-id-shaped identifier. The journey ledger
    (serving/journey.py) keys untraced requests with one of these so a
    journey id is always trace-id-shaped — ``/journey/{id}`` consumers
    never need to care whether the request was traced."""
    return _hex_id(16)


@dataclass(frozen=True)
class TraceContext:
    """One (trace, parent-span) coordinate — what the header encodes."""

    trace_id: str  # 32 hex chars
    span_id: str   # 16 hex chars

    @classmethod
    def new(cls) -> "TraceContext":
        return cls(trace_id=_hex_id(16), span_id=_hex_id(8))

    @classmethod
    def parse(cls, header: Any) -> "TraceContext | None":
        """Parse a ``langstream-trace`` / traceparent value; None when the
        value is absent or malformed (a bad client header must not 500 the
        gateway — it just starts a fresh trace)."""
        if not isinstance(header, str):
            return None
        parts = header.strip().split("-")
        if len(parts) != 4:
            return None
        version, trace_id, span_id, _flags = parts
        if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
            return None
        try:
            int(trace_id, 16), int(span_id, 16)
        except ValueError:
            return None
        if trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        return cls(trace_id=trace_id, span_id=span_id)

    def to_header(self) -> str:
        return f"{_VERSION}-{self.trace_id}-{self.span_id}-{_FLAGS}"

    def child(self) -> "TraceContext":
        """Same trace, fresh span id (the id a new child span takes)."""
        return TraceContext(trace_id=self.trace_id, span_id=_hex_id(8))


# ---------------------------------------------------------------------------
# ambient context (per-record, task-scoped)
# ---------------------------------------------------------------------------

_current: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "langstream_trace_context", default=None
)


def current_context() -> TraceContext | None:
    return _current.get()


def set_current(ctx: TraceContext | None) -> contextvars.Token:
    return _current.set(ctx)


def reset_current(token: contextvars.Token) -> None:
    try:
        _current.reset(token)
    except ValueError:
        # token from another context (callback crossed tasks): best-effort
        _current.set(None)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


class Span:
    """One timed operation. ``end()`` is idempotent and never raises; an
    unfinished span simply never reaches the buffer (no half-open junk in
    ``/traces``)."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "service",
        "attributes", "error", "_start_mono", "_start_wall_ms", "_ended",
    )

    def __init__(
        self,
        name: str,
        service: str,
        trace_id: str,
        span_id: str,
        parent_id: str | None,
        attributes: dict[str, Any] | None = None,
    ):
        self.name = name
        self.service = service
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attributes = dict(attributes) if attributes else {}
        self.error: str | None = None
        self._start_mono = time.monotonic()
        # wall clock anchors the span on a human timeline only; durations
        # below are monotonic-only (OBS501 is the gate for that rule)
        self._start_wall_ms = time.time() * 1000.0
        self._ended = False

    def context(self) -> TraceContext:
        """This span as a parent coordinate — what gets stamped into the
        record header so downstream spans nest under it."""
        return TraceContext(trace_id=self.trace_id, span_id=self.span_id)

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def end(self, error: BaseException | str | None = None) -> float:
        """Finish the span; returns its duration in seconds. Idempotent:
        a second end keeps the first timing."""
        duration_s = time.monotonic() - self._start_mono
        if self._ended:
            return duration_s
        self._ended = True
        if isinstance(error, BaseException):
            self.error = str(error) or error.__class__.__name__
        elif error is not None:
            self.error = str(error)
        try:
            SPANS.add(self._to_dict(duration_s))
        except Exception:  # tracing must never break the traced path
            log.debug("span buffer append failed", exc_info=True)
        return duration_s

    def _to_dict(self, duration_s: float) -> dict[str, Any]:
        out: dict[str, Any] = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "service": self.service,
            "start_ms": round(self._start_wall_ms, 3),
            "duration_ms": round(duration_s * 1000.0, 3),
        }
        if self.attributes:
            out["attributes"] = self.attributes
        if self.error:
            out["error"] = self.error
        return out

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end(error=exc)


def start_span(
    name: str,
    service: str,
    parent: "TraceContext | Span | str | None" = None,
    attributes: dict[str, Any] | None = None,
) -> Span:
    """Open a span. ``parent`` may be a context, another span, a raw header
    value, or None — None falls back to the ambient context, then to a
    fresh root trace."""
    if isinstance(parent, Span):
        ctx: TraceContext | None = parent.context()
    elif isinstance(parent, TraceContext):
        ctx = parent
    else:
        # raw header value (or junk a client sent): parse returns None on
        # anything malformed, falling back to ambient/new-root below
        ctx = TraceContext.parse(parent)
    if ctx is None:
        ctx = current_context()
    if ctx is None:
        return Span(
            name, service,
            trace_id=_hex_id(16), span_id=_hex_id(8), parent_id=None,
            attributes=attributes,
        )
    return Span(
        name, service,
        trace_id=ctx.trace_id, span_id=_hex_id(8), parent_id=ctx.span_id,
        attributes=attributes,
    )


def record_span(
    name: str,
    service: str,
    parent: "TraceContext | Span | str | None",
    start_monotonic: float,
    end_monotonic: float,
    attributes: dict[str, Any] | None = None,
) -> None:
    """Record a span retroactively from monotonic timestamps already taken
    (the serving engine's queue/prefill/decode phases are measured by its
    own request timestamps; spans are materialized at completion). Never
    raises."""
    try:
        span = start_span(name, service, parent=parent, attributes=attributes)
        duration_s = max(0.0, end_monotonic - start_monotonic)
        # re-anchor: start_ms was stamped "now"; shift it back to the real
        # phase start on the shared monotonic axis
        span._start_wall_ms -= (time.monotonic() - start_monotonic) * 1000.0
        span._ended = True
        SPANS.add(span._to_dict(duration_s))
    except Exception:
        log.debug("record_span failed", exc_info=True)


# ---------------------------------------------------------------------------
# span ring buffer + JSONL export
# ---------------------------------------------------------------------------


class SpanBuffer:
    """Bounded, thread-safe buffer of finished spans (as plain dicts).

    Process-global by design: one pod = one process = one buffer, which is
    what the pod's ``/traces`` endpoints serve; in dev mode every in-process
    agent shares it, which is what the control plane aggregates."""

    def __init__(self, maxlen: int = 2048):
        self._spans: deque[dict[str, Any]] = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._export_path = os.environ.get("LS_TPU_TRACE_LOG")
        self._export_file = None
        self._export_broken = False
        # JSONL export is decoupled from span ends by a bounded queue and
        # one daemon writer thread: a slow/contended disk must not stall
        # the event loop per span (spans end on the gateway/engine loops),
        # and a single writer is what keeps lines from interleaving
        self._export_queue: deque[dict[str, Any]] = deque(maxlen=8192)
        self._export_wake = threading.Event()
        self._export_idle = threading.Event()
        self._export_idle.set()
        self._export_thread: threading.Thread | None = None

    def add(self, span: dict[str, Any]) -> None:
        with self._lock:
            self._spans.append(span)
            if self._export_path and not self._export_broken:
                self._export_queue.append(span)
                self._export_idle.clear()
                if self._export_thread is None:
                    self._export_thread = threading.Thread(
                        target=self._export_loop,
                        name="ls-tpu-trace-export",
                        daemon=True,
                    )
                    self._export_thread.start()
                self._export_wake.set()

    def snapshot(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._spans)

    def spans(self, trace_id: str) -> list[dict[str, Any]]:
        """All buffered spans of one trace, oldest first."""
        return [s for s in self.snapshot() if s.get("trace_id") == trace_id]

    def summaries(self) -> list[dict[str, Any]]:
        """Per-trace rollup for the ``/traces`` index: span count, services
        touched, the root-most span name, and total wall span."""
        by_trace: dict[str, list[dict[str, Any]]] = {}
        for span in self.snapshot():
            by_trace.setdefault(span["trace_id"], []).append(span)
        out = []
        for trace_id, spans in by_trace.items():
            ids = {s["span_id"] for s in spans}
            roots = [s for s in spans if s.get("parent_id") not in ids]
            root = min(
                roots or spans, key=lambda s: s.get("start_ms", 0.0)
            )
            start = min(s.get("start_ms", 0.0) for s in spans)
            end = max(
                s.get("start_ms", 0.0) + s.get("duration_ms", 0.0)
                for s in spans
            )
            out.append(
                {
                    "trace_id": trace_id,
                    "spans": len(spans),
                    "root": root.get("name"),
                    "services": sorted({s.get("service", "") for s in spans}),
                    "start_ms": start,
                    "duration_ms": round(end - start, 3),
                    "errors": sum(1 for s in spans if s.get("error")),
                }
            )
        out.sort(key=lambda t: t["start_ms"])
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def drain_export(self, timeout: float = 5.0) -> bool:
        """Block until every queued span reached the JSONL file (or the
        sink broke). For tests and orderly shutdown; True when drained."""
        return self._export_idle.wait(timeout)

    def _export_loop(self) -> None:
        while True:
            self._export_wake.wait()
            self._export_wake.clear()
            while True:
                with self._lock:
                    if not self._export_queue:
                        self._export_idle.set()
                        break
                    span = self._export_queue.popleft()
                # the write itself runs outside the lock: span ends only
                # contend on a queue append, never on disk
                self._write_line(span)

    def _write_line(self, span: dict[str, Any]) -> None:
        if self._export_broken:
            return
        try:
            if self._export_file is None:
                self._export_file = open(  # noqa: SIM115 — long-lived sink
                    self._export_path, "a", encoding="utf-8"
                )
            self._export_file.write(json.dumps(span) + "\n")
            self._export_file.flush()
        except OSError as e:
            # one warning, then stay silent: an unwritable trace log must
            # not turn into a per-span error storm in the serving path
            self._export_broken = True
            with self._lock:
                self._export_queue.clear()
            log.warning("trace JSONL export disabled (%s): %s",
                        self._export_path, e)


def _buffer_size() -> int:
    try:
        return max(64, int(os.environ.get("LS_TPU_TRACE_BUFFER", "2048")))
    except ValueError:
        return 2048


#: the process-global buffer the pod ``/traces`` endpoints serve
SPANS = SpanBuffer(maxlen=_buffer_size())
