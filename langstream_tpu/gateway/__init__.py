"""L6 gateway: the client-facing WebSocket/HTTP front door.

Parity: ``langstream-api-gateway`` — WS endpoints
``/v1/{consume,produce,chat}/{tenant}/{application}/{gateway}``
(``websocket/WebSocketConfig.java:47-49``), HTTP produce + service endpoints
(``http/GatewayResource.java:72-95``), gateway-level authentication
providers, header injection from client parameters
(``value-from-parameters``) and from the authenticated principal
(``value-from-authentication``), server-side consume filters, and client
lifecycle events to an events topic (``EventRecord.java:29-44``).
"""

from langstream_tpu.gateway.server import GatewayServer

__all__ = ["GatewayServer"]
