"""API-gateway service entrypoint (the deploy manifests run this).

    python -m langstream_tpu.gateway

Env: ``LS_PORT`` (default 8091), ``LS_CONTROL_PLANE_URL`` — the gateway
keeps its application registry in sync by polling the control plane's
application list (the reference's gateway reads the same store the
webservice writes; over HTTP here so the two services stay independently
deployable).
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal

log = logging.getLogger(__name__)


async def _sync_registry(registry, control_plane_url: str) -> None:
    """Poll the control plane and keep the gateway registry consistent:
    deployed apps (re)register, deleted apps unregister. When the control
    plane runs with admin auth, ``LS_CONTROL_PLANE_TOKEN`` carries the
    bearer token; that same auth is what entitles the sync to the full view
    including secrets (placeholder resolution for gateway auth configs)."""
    import aiohttp

    from langstream_tpu.controlplane.server import parse_stored
    from langstream_tpu.controlplane.stores import StoredApplication

    from langstream_tpu.core.placeholders import resolve_placeholders

    headers = {}
    token = os.environ.get("LS_CONTROL_PLANE_TOKEN")
    if token:
        headers["Authorization"] = f"Bearer {token}"
    known: dict[tuple[str, str], str] = {}

    async def sync_one(session, tenant: str, app_name: str) -> None:
        async with session.get(
            f"{control_plane_url}/api/applications/{tenant}/"
            f"{app_name}?files=true"
        ) as resp:
            body = await resp.json()
        files = body.get("files") or {}
        # fingerprint the whole deployable state: instance/secrets-only
        # updates (broker moves, credential rotation) must propagate too
        fingerprint = str(
            (sorted(files.items()), body.get("instance"), body.get("secrets"))
        )
        if known.get((tenant, app_name)) == fingerprint:
            return
        stored = StoredApplication(
            tenant=tenant,
            name=app_name,
            files=files,
            instance=body.get("instance"),
            secrets=body.get("secrets"),
        )
        application = parse_stored(stored)
        # the gateway serves the RESOLVED app (auth configs and streaming
        # clusters reference ${secrets.*}/${globals.*}) — exactly what the
        # compute runtime resolves before deploying. Fail CLOSED on
        # unresolvable placeholders: serving a gateway whose auth secret is
        # the literal '${secrets...}' string would let anyone who reads the
        # config pass authentication.
        try:
            resolve_placeholders(application)
        except Exception as e:
            log.error(
                "app %s/%s not served: %s. The control plane withholds "
                "secrets unless admin auth is enabled — set LS_ADMIN_AUTH "
                "on the control plane and LS_CONTROL_PLANE_TOKEN here.",
                tenant, app_name, e,
            )
            known[(tenant, app_name)] = fingerprint  # don't retry-spam
            return
        registry.register(tenant, app_name, application)
        known[(tenant, app_name)] = fingerprint

    async def sync_fleet(session, tenant: str, app_name: str) -> None:
        """Replica-router feed (docs/FLEET.md): the control plane's
        autoscaler already fans in per-replica observations — the
        gateway consumes the same snapshot for least-loaded routing and
        session affinity. Polled only for apps whose own resources
        declare an enabled ``autoscale:`` section — everything else
        would answer ``{"enabled": false}`` forever, and N apps x one
        extra round-trip per 5 s tick is pure waste. The 5 s cadence
        keeps snapshots inside the router's 15 s freshness window."""
        from langstream_tpu.controlplane.autoscaler import (
            application_autoscale_spec,
        )

        app = registry.application(tenant, app_name)
        if app is None or application_autoscale_spec(app) is None:
            return
        async with session.get(
            f"{control_plane_url}/api/applications/{tenant}/"
            f"{app_name}/autoscaler"
        ) as resp:
            body = await resp.json()
        if body.get("enabled") and body.get("replicas"):
            registry.update_fleet(tenant, app_name, body["replicas"])
        elif body.get("enabled") and body.get("pools"):
            # disaggregated app (docs/DISAGG.md): one autoscaler status
            # per pool — feed each pool's replicas under its own source
            # so the router keeps the union of both pools
            for pool, status in body["pools"].items():
                if status.get("replicas"):
                    registry.update_fleet(
                        tenant, app_name, status["replicas"], source=pool
                    )

    async with aiohttp.ClientSession(headers=headers) as session:
        while True:
            try:
                async with session.get(
                    f"{control_plane_url}/api/tenants"
                ) as resp:
                    tenants = await resp.json()
                current: set[tuple[str, str]] = set()
                for tenant in tenants:
                    async with session.get(
                        f"{control_plane_url}/api/applications/{tenant}"
                    ) as resp:
                        apps = await resp.json()
                    for app_name in apps:
                        current.add((tenant, app_name))
                        try:
                            # one broken app must not block the rest of the
                            # sync (or the unregistration pass below)
                            await sync_one(session, tenant, app_name)
                        except Exception as e:
                            log.warning(
                                "sync of %s/%s failed: %s", tenant, app_name, e
                            )
                            continue
                        try:
                            await sync_fleet(session, tenant, app_name)
                        except Exception as e:
                            # the registration above stands — a failed
                            # fleet poll only leaves the router feed
                            # stale, and the 15 s freshness window
                            # degrades that to stamping nothing
                            log.debug(
                                "fleet sync of %s/%s failed: %s",
                                tenant, app_name, e,
                            )
                # deleted apps must stop resolving (their gateways would
                # otherwise keep serving stale topic access forever)
                for tenant, app_name in set(known) - current:
                    registry.unregister(tenant, app_name)
                    del known[(tenant, app_name)]
            except Exception as e:
                log.warning("registry sync failed: %s", e)
            await asyncio.sleep(5)


async def main() -> None:
    from langstream_tpu.gateway.server import GatewayRegistry, GatewayServer

    port = int(os.environ.get("LS_PORT", "8091"))
    registry = GatewayRegistry()
    server = GatewayServer(
        registry=registry, port=port,
        host=os.environ.get("LS_BIND", "0.0.0.0"),
    )
    await server.start()
    log.info("api gateway up on :%d", port)
    sync_task = None
    control_plane = os.environ.get("LS_CONTROL_PLANE_URL")
    if control_plane:
        sync_task = asyncio.ensure_future(
            _sync_registry(registry, control_plane.rstrip("/"))
        )
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if sync_task is not None:
        sync_task.cancel()
    await server.stop()


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    asyncio.run(main())
