"""Gateway authentication providers.

Parity: ``langstream-api-gateway-auth`` (google/github/jwt/http providers).
First-party: ``http`` (POST credentials to a verification endpoint) and
``test`` (accept-all, principal echoes the credentials — the fixture role
the reference's tests play). ``google``/``github``/``jwt`` gate on network
or optional libraries.
"""

from __future__ import annotations

import abc
from collections import OrderedDict
from typing import Any


class AuthenticationException(Exception):
    pass


class GatewayAuthenticationProvider(abc.ABC):
    """authenticate(credentials) → principal claims dict (raises on deny)."""

    def __init__(self, configuration: dict[str, Any]):
        self.configuration = configuration

    @abc.abstractmethod
    async def authenticate(self, credentials: str | None) -> dict[str, Any]: ...


class TestAuthenticationProvider(GatewayAuthenticationProvider):
    """Accept-all provider for tests/dev: principal.subject = credentials."""

    async def authenticate(self, credentials: str | None) -> dict[str, Any]:
        if self.configuration.get("require-credentials") and not credentials:
            raise AuthenticationException("credentials required")
        return {"subject": credentials or "anonymous"}


class HttpAuthenticationProvider(GatewayAuthenticationProvider):
    """POSTs the credentials to an external endpoint; 2xx → principal from
    the JSON response (parity: the reference's http auth provider)."""

    async def authenticate(self, credentials: str | None) -> dict[str, Any]:
        import aiohttp

        url = self.configuration.get("base-url", "") + self.configuration.get(
            "path-template", "/check"
        )
        async with aiohttp.ClientSession() as session:
            async with session.post(url, json={"token": credentials}) as resp:
                if resp.status >= 300:
                    raise AuthenticationException(f"auth endpoint: {resp.status}")
                try:
                    data = await resp.json()
                except Exception:
                    data = {}
        return data if isinstance(data, dict) else {"subject": str(data)}


_PROVIDERS: dict[str, type[GatewayAuthenticationProvider]] = {
    "test": TestAuthenticationProvider,
    "http": HttpAuthenticationProvider,
}


def register_auth_provider(name: str, cls: type[GatewayAuthenticationProvider]) -> None:
    _PROVIDERS[name] = cls


def _ensure_providers() -> None:
    if "jwt" not in _PROVIDERS:
        from langstream_tpu.auth.providers import (
            GithubAuthenticationProvider,
            GoogleAuthenticationProvider,
            JwtAuthenticationProvider,
        )

        _PROVIDERS["jwt"] = JwtAuthenticationProvider
        _PROVIDERS["google"] = GoogleAuthenticationProvider
        _PROVIDERS["github"] = GithubAuthenticationProvider


# Constructed providers are memoized by (name, config): gateways resolve
# their provider on every request, and per-request construction would both
# rebuild validator state (defeating e.g. the google JWKS cache) and defer
# construction-time config validation to the first login. LRU-bounded so
# rotated secrets/configs don't pin provider objects for process lifetime.
_INSTANCES: OrderedDict[tuple[str, str], GatewayAuthenticationProvider] = (
    OrderedDict()
)
_INSTANCES_MAX = 64


def get_auth_provider(
    name: str, configuration: dict[str, Any]
) -> GatewayAuthenticationProvider:
    import json

    _ensure_providers()
    if name not in _PROVIDERS:
        raise AuthenticationException(
            f"unknown auth provider {name!r}; available: {sorted(_PROVIDERS)} "
            f"(google/github need outbound network)"
        )
    key = (name, json.dumps(configuration, sort_keys=True, default=str))
    provider = _INSTANCES.get(key)
    if provider is None:
        provider = _INSTANCES[key] = _PROVIDERS[name](configuration)
    _INSTANCES.move_to_end(key)
    while len(_INSTANCES) > _INSTANCES_MAX:
        _INSTANCES.popitem(last=False)
    return provider


def validate_gateway_authentication(gateways) -> None:
    """Construct every gateway's auth provider once at deploy/update
    validation time so misconfiguration (e.g. google without clientId)
    fails the deploy instead of surfacing as per-login 401s."""
    for gw in gateways or []:
        auth = getattr(gw, "authentication", None)
        if not auth:
            continue
        name = auth.get("provider", "test")
        try:
            get_auth_provider(name, auth.get("configuration", {}))
        except AuthenticationException as e:
            gw_id = getattr(gw, "id", None) or "?"
            raise ValueError(
                f"gateway {gw_id!r}: invalid authentication ({name}): {e}"
            ) from e
