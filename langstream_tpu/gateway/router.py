"""Replica-aware routing for serving traffic (docs/FLEET.md).

The gateway produces into a topic; with one serving replica that is the
whole story, but a fleet needs the record to land on the replica best
placed to serve it. This module is the gateway's half of that loop:

- :class:`ReplicaRouter` tracks per-replica flight snapshots (the same
  observation dicts the autoscaler consumes — queue depth, occupancy,
  health/drain posture) and picks the **least-loaded eligible** replica:
  load = ``(1 + queue depth) × (1 + occupancy/slots)``, monotone in both
  axes so a deep queue and a full batch each push traffic away.
  Draining, wedged, and unreachable replicas are never eligible — a
  record routed into a dying pod's queue is a record the drain has to
  requeue right back.
- **Session affinity** on the QoS tenant (``langstream-qos-tenant``): a
  conversation keeps hitting the replica that already holds its
  prefix-cache blocks (ROADMAP item 3's warm-TTFT lever), for as long as
  the replica stays eligible and the affinity entry is fresh. Affinity
  is advisory: an ineligible replica breaks it immediately and the
  session re-pins to the new least-loaded pick.
- **Prefix affinity** on the chained prompt-prefix digest the gateway
  stamps (``langstream-prefix-digest``, serving/prefixstore.py): repeat
  traffic for one shared system prompt lands on the replica whose
  tiered prefix store already holds its blocks — across DIFFERENT
  tenants, which tenant affinity cannot see (N tenants sharing a
  preamble is exactly the shape the prefix tiers exist for,
  docs/PREFIX.md). More specific than the tenant pin, so it is
  consulted first; prefix-less traffic takes the pre-existing path
  bit for bit.
- The choice is stamped as the ``langstream-replica`` record header; the
  serving agent's consumer honors it (``runtime/runner.py``): a replica
  that reads a record stamped for a sibling re-produces it back to the
  input topic (bounce-capped) so partition assignment and routing intent
  converge instead of fighting.

Snapshots arrive via :meth:`observe` — pushed by whoever already has
them (the control plane's autoscaler loop, a gateway-side poller, tests)
— and go stale after ``fresh_s``: routing on stale evidence is worse
than no routing, so a router with no fresh snapshot stamps nothing and
the topic's normal partition spread applies.

Stdlib-only, no locks: the router lives on the gateway's event loop;
every method is dict arithmetic (the same wait-free posture the health
plane keeps, and for the same reason — routing runs on the produce hot
path).
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque
from typing import Any, Callable

from langstream_tpu.serving.handoff import (
    OPEN,
    BreakerSpec,
    CircuitBreaker,
)

#: record header carrying the routing choice; the serving agent's
#: consumer honors it (see runtime/runner.py)
REPLICA_HEADER = "langstream-replica"
#: reroute loop guard: bounces a stamped record may take before the
#: consumer serves it locally anyway (better the wrong replica than a
#: record orbiting the topic after its target vanished)
BOUNCE_HEADER = "langstream-replica-bounces"
MAX_BOUNCES = 2


class ReplicaRouter:
    """Least-loaded replica choice with tenant session affinity."""

    #: max tenants pinned before LRU eviction — tenant names can be
    #: client-chosen on unauthenticated gateways (same bound the QoS
    #: limiter keeps)
    MAX_AFFINITY = 4096

    def __init__(
        self,
        fresh_s: float = 15.0,
        affinity_ttl_s: float = 600.0,
        clock: Callable[[], float] = time.monotonic,
        breaker: BreakerSpec | None = None,
    ):
        self.fresh_s = fresh_s
        self.affinity_ttl_s = affinity_ttl_s
        self._clock = clock
        # circuit breakers (serving/handoff.py, docs/RESILIENCE.md):
        # one per replica, created lazily on the FIRST report_failure/
        # report_success — a router nobody reports outcomes to (the
        # classic produce-only gateway) carries an empty dict and routes
        # bit-for-bit as before
        self.breaker_spec = breaker or BreakerSpec()
        self._breakers: dict[str, CircuitBreaker] = {}
        # 503 Retry-After holds: replica -> monotonic release stamp. A
        # replica that shed WITH a hint is not re-offered until the hint
        # elapses (`exclude=` only ever lasted one pick)
        self._holds: dict[str, float] = {}
        self.holds_applied = 0
        # breaker transitions, newest last — stats() serves the tail and
        # on_breaker_event (when wired, e.g. by the handoff chainer)
        # mirrors each into a flight ring
        self.events: deque = deque(maxlen=64)
        self.on_breaker_event: Callable[[str, str, dict], None] | None = None
        # network fault seam (serving/faults.py `route` site): tests arm
        # an injector so a routing outage is a deterministic input
        self.fault_injector = None
        self._replicas: dict[str, dict[str, Any]] = {}
        self._observed_at: float | None = None
        # tenant -> [replica, pinned_at]
        self._affinity: "OrderedDict[str, list]" = OrderedDict()
        # prompt-prefix digest -> [replica, pinned_at] (docs/PREFIX.md):
        # bounded like the tenant map — digests derive from prompt text,
        # which clients control
        self._prefix_affinity: "OrderedDict[str, list]" = OrderedDict()
        # adapter name -> [replica, pinned_at] (docs/ADAPTERS.md): a
        # tenant's LoRA adapter rides the replica whose device/T1 tiers
        # already hold it — re-routing pays a T2 hydration, so the pin
        # sits beside the prefix pin and is bounded the same way
        self._adapter_affinity: "OrderedDict[str, list]" = OrderedDict()
        self.picks = 0
        self.affinity_hits = 0
        self.affinity_rerouted = 0
        self.prefix_hits = 0
        self.prefix_rerouted = 0
        self.adapter_hits = 0
        self.adapter_rerouted = 0
        # disaggregated pools (docs/DISAGG.md): the phase of the latest
        # pick ("prefill"/"decode"/"any") — engine_top's split-fleet view
        self.last_pick_phase: str | None = None

    # -- snapshot ingestion ---------------------------------------------

    def observe(self, snapshots: list[dict[str, Any]]) -> None:
        """Replace the fleet view with fresh per-replica observation
        dicts (the :class:`~langstream_tpu.controlplane.autoscaler.
        ReplicaObservation` shape: ``replica``/``queued``/``occupancy``/
        ``slots``/``state``/``draining``/``unreachable``)."""
        self._replicas = {
            s["replica"]: dict(s) for s in snapshots if s.get("replica")
        }
        self._observed_at = self._clock()

    def fresh(self) -> bool:
        return (
            self._observed_at is not None
            and self._clock() - self._observed_at <= self.fresh_s
        )

    # -- choice ----------------------------------------------------------

    @staticmethod
    def _eligible(snapshot: dict[str, Any]) -> bool:
        return not (
            snapshot.get("unreachable")
            or snapshot.get("draining")
            or snapshot.get("state") == "wedged"
        )

    # -- failure feedback: breakers + Retry-After holds ------------------

    def _emit_breaker(self, kind: str, replica: str, **detail: Any) -> None:
        detail["open_replicas"] = sum(
            1 for b in self._breakers.values() if b.state == OPEN
        )
        entry = {"kind": kind, "replica": replica, "m_s": self._clock(),
                 **detail}
        self.events.append(entry)
        if self.on_breaker_event is not None:
            self.on_breaker_event(kind, replica, detail)

    def report_failure(self, replica: str, kind: str = "error") -> None:
        """One failed call against ``replica`` (timeout / refused / bad
        HTTP): feeds its rolling breaker window; enough inside the
        window flip it OPEN and it leaves every subsequent ``pick``
        until a half-open probe proves it back (docs/RESILIENCE.md)."""
        breaker = self._breakers.get(replica)
        if breaker is None:
            breaker = self._breakers[replica] = CircuitBreaker(
                self.breaker_spec, clock=self._clock
            )
        before = breaker.state
        after = breaker.record_failure(kind)
        if after == OPEN and before != OPEN:
            self._emit_breaker(
                "breaker-open", replica,
                failures=breaker.stats()["window_failures"],
                last_kind=kind,
            )

    def report_success(self, replica: str) -> None:
        """One successful call: closes a half-open breaker (the probe
        proved the replica back) and clears the failure window."""
        breaker = self._breakers.get(replica)
        if breaker is None:
            return
        before = breaker.state
        after = breaker.record_success()
        if before != after:
            self._emit_breaker("breaker-close", replica)

    def hold(self, replica: str, retry_after_s: float) -> None:
        """503 ``Retry-After`` hold: the replica shed WITH a hint, so it
        is not re-offered until the hint elapses — a plain ``exclude=``
        only lasted one pick, and the next pick walked straight back
        into the saturated replica."""
        self._holds[replica] = self._clock() + max(0.0, retry_after_s)
        self.holds_applied += 1

    def _routable(self, name: str, now: float) -> bool:
        """Breaker + hold gate on top of snapshot eligibility. Expired
        holds are dropped here (the map self-cleans on the pick path)."""
        until = self._holds.get(name)
        if until is not None:
            if now < until:
                return False
            del self._holds[name]
        breaker = self._breakers.get(name)
        return breaker is None or breaker.can_serve(now)

    def _chosen(self, name: str) -> str:
        """Account the pick against a half-open breaker's probe budget
        (only a pick that actually routes traffic burns a probe)."""
        breaker = self._breakers.get(name)
        if breaker is not None:
            breaker.note_probe()
        return name

    @staticmethod
    def _pool(snapshot: dict[str, Any]) -> str:
        return snapshot.get("pool") or "combined"

    def _pooled(self) -> bool:
        """True once any replica declares a split pool role — the
        moment phase filtering engages. Combined-only fleets never see
        it, so today's behavior stays bit-for-bit."""
        return any(
            self._pool(snap) != "combined"
            for snap in self._replicas.values()
        )

    def _phase_ok(self, snapshot: dict[str, Any], phase: str | None) -> bool:
        """Phase filter (disaggregated fleets only): new requests go to
        the prefill pool, handoffs to the decode pool; a combined
        replica in a mixed fleet serves either phase."""
        if phase is None or not self._pooled():
            return True
        pool = self._pool(snapshot)
        return pool == phase or pool == "combined"

    @staticmethod
    def _load(snapshot: dict[str, Any]) -> float:
        """(1 + queue depth) × (1 + occupancy/slots): a replica with an
        empty queue and an empty batch scores 1.0; queue growth scales
        the score linearly, batch fullness up to 2×."""
        slots = snapshot.get("slots") or 0
        occ_frac = (snapshot.get("occupancy") or 0) / slots if slots else 0.0
        return (1.0 + (snapshot.get("queued") or 0)) * (1.0 + occ_frac)

    def eligible(self) -> list[str]:
        return sorted(
            name
            for name, snap in self._replicas.items()
            if self._eligible(snap)
        )

    def pick(
        self,
        tenant: str | None = None,
        phase: str | None = None,
        exclude: Any = (),
        prefix: str | None = None,
        adapter: str | None = None,
    ) -> str | None:
        """The replica for one record: the tenant's pinned replica while
        it stays eligible and fresh, else the least-loaded eligible
        replica (ties break on name for determinism). ``None`` when the
        fleet view is stale or empty — stamp nothing, let the topic's
        partition spread route.

        ``phase`` (disaggregated fleets, docs/DISAGG.md) restricts the
        choice to that pool — ``"prefill"`` for new requests,
        ``"decode"`` for KV handoff targets; it is a no-op while every
        replica is ``combined``, so a classic fleet's routing stays
        bit-for-bit. ``exclude`` names replicas the caller already tried
        (a decode replica that answered 503 — retry the next one).

        ``prefix`` (the gateway's chained prompt-prefix digest,
        docs/PREFIX.md) pins MORE specifically than the tenant: repeat
        traffic for one shared system prompt returns to the replica
        whose prefix tiers hold its blocks, whatever tenant sent it.
        Consulted before the tenant pin; ``None`` (prefix-less traffic)
        leaves the pre-existing choice bit for bit.

        ``adapter`` (the gateway's ``langstream-adapter`` stamp,
        docs/ADAPTERS.md) pins the tenant's LoRA adapter to the replica
        whose adapter tiers already hold it — a re-route costs a T2
        hydration plus a device-row load, which is the multi-LoRA
        analogue of a cold prefix. Consulted after the prefix pin
        (an exact shared-prompt match is stronger evidence) and before
        the tenant pin; adapter-less traffic is untouched."""
        if self.fault_injector is not None:
            # deterministic routing outage (serving/faults.py `route`
            # site): drop = no pick this pass, error = the registry blew
            # up — both shapes chaos tests aim at the chainer's
            # no-healthy-replica path
            action = self.fault_injector.fire("route")
            if action is not None:
                if action.shape == "error":
                    raise RuntimeError(action.message)
                if action.shape == "delay-ms":
                    time.sleep(action.hang_ms / 1000.0)  # graftcheck: disable=PFX801 injected routing stall (tests/chaos only; a production router carries no injector)
                else:
                    return None
        if not self.fresh():
            return None
        now = self._clock()
        exclude = set(exclude or ())
        candidates = [
            (self._load(snap), name)
            for name, snap in self._replicas.items()
            if self._eligible(snap)
            and self._phase_ok(snap, phase)
            and name not in exclude
            and self._routable(name, now)
        ]
        if not candidates:
            return None
        self.last_pick_phase = phase or "any"
        if phase == "decode":
            # handoff targets are pure least-loaded: session affinity is
            # a prefix-cache-locality lever, and prefix blocks live on
            # the PREFILL pool — pinning decode picks under the tenant
            # would thrash the prefill pin instead
            self.picks += 1
            return self._chosen(min(candidates)[1])
        if prefix:
            pinned = self._prefix_affinity.get(prefix)
            if pinned is not None:
                replica, pinned_at = pinned
                snap = self._replicas.get(replica)
                if (
                    snap is not None
                    and self._eligible(snap)
                    and self._phase_ok(snap, phase)
                    and replica not in exclude
                    and self._routable(replica, now)
                    and now - pinned_at <= self.affinity_ttl_s
                ):
                    # the replica already holding this prompt's prefix
                    # blocks (T0/T1/T2 — docs/PREFIX.md): warm TTFT
                    # beats load spread for shared-preamble traffic
                    pinned[1] = now
                    self._prefix_affinity.move_to_end(prefix)
                    self.picks += 1
                    self.prefix_hits += 1
                    if tenant:
                        # keep the tenant pin converged on the same
                        # replica so the two affinity maps never fight
                        self._pin_tenant(tenant, replica, now)
                    if adapter:
                        self._pin_adapter(adapter, replica, now)
                    return self._chosen(replica)
                self.prefix_rerouted += 1
        if adapter:
            pinned = self._adapter_affinity.get(adapter)
            if pinned is not None:
                replica, pinned_at = pinned
                snap = self._replicas.get(replica)
                if (
                    snap is not None
                    and self._eligible(snap)
                    and self._phase_ok(snap, phase)
                    and replica not in exclude
                    and self._routable(replica, now)
                    and now - pinned_at <= self.affinity_ttl_s
                ):
                    # the replica whose adapter tiers already hold this
                    # fine-tune (device rows or T1 host RAM): warm
                    # adapter TTFT beats load spread (docs/ADAPTERS.md)
                    pinned[1] = now
                    self._adapter_affinity.move_to_end(adapter)
                    self.picks += 1
                    self.adapter_hits += 1
                    if tenant:
                        self._pin_tenant(tenant, replica, now)
                    return self._chosen(replica)
                self.adapter_rerouted += 1
        if tenant:
            pinned = self._affinity.get(tenant)
            if pinned is not None:
                replica, pinned_at = pinned
                snap = self._replicas.get(replica)
                if (
                    snap is not None
                    and self._eligible(snap)
                    and self._phase_ok(snap, phase)
                    and replica not in exclude
                    and self._routable(replica, now)
                    and now - pinned_at <= self.affinity_ttl_s
                ):
                    # refresh the pin: an active conversation keeps its
                    # prefix-cache locality for as long as it stays warm
                    pinned[1] = now
                    self._affinity.move_to_end(tenant)
                    self.picks += 1
                    self.affinity_hits += 1
                    if prefix:
                        self._pin_prefix(prefix, replica, now)
                    if adapter:
                        self._pin_adapter(adapter, replica, now)
                    return self._chosen(replica)
                self.affinity_rerouted += 1
        choice = min(candidates)[1]
        self.picks += 1
        if tenant:
            self._pin_tenant(tenant, choice, now)
        if prefix:
            self._pin_prefix(prefix, choice, now)
        if adapter:
            self._pin_adapter(adapter, choice, now)
        return self._chosen(choice)

    def _pin_tenant(self, tenant: str, replica: str, now: float) -> None:
        self._affinity[tenant] = [replica, now]
        self._affinity.move_to_end(tenant)
        while len(self._affinity) > self.MAX_AFFINITY:
            self._affinity.popitem(last=False)

    def _pin_prefix(self, prefix: str, replica: str, now: float) -> None:
        self._prefix_affinity[prefix] = [replica, now]
        self._prefix_affinity.move_to_end(prefix)
        while len(self._prefix_affinity) > self.MAX_AFFINITY:
            self._prefix_affinity.popitem(last=False)

    def _pin_adapter(self, adapter: str, replica: str, now: float) -> None:
        self._adapter_affinity[adapter] = [replica, now]
        self._adapter_affinity.move_to_end(adapter)
        while len(self._adapter_affinity) > self.MAX_AFFINITY:
            self._adapter_affinity.popitem(last=False)

    # -- introspection ---------------------------------------------------

    def stats(self) -> dict[str, Any]:
        # per-pool eligibility census: the split-fleet view engine_top's
        # pools panel renders (combined-only fleets report one
        # "combined" row — the pre-disaggregation shape, just grouped)
        pools: dict[str, dict[str, int]] = {}
        for snap in self._replicas.values():
            entry = pools.setdefault(
                self._pool(snap), {"replicas": 0, "eligible": 0}
            )
            entry["replicas"] += 1
            if self._eligible(snap):
                entry["eligible"] += 1
        return {
            "replicas": {
                name: {
                    "load": round(self._load(snap), 3),
                    "eligible": self._eligible(snap),
                    "queued": snap.get("queued", 0),
                    "occupancy": snap.get("occupancy", 0),
                    "draining": bool(snap.get("draining")),
                    "state": snap.get("state", "ok"),
                    "unreachable": bool(snap.get("unreachable")),
                    "pool": self._pool(snap),
                }
                for name, snap in sorted(self._replicas.items())
            },
            "pools": {k: pools[k] for k in sorted(pools)},
            "last_pick_phase": self.last_pick_phase,
            "fresh": self.fresh(),
            "picks": self.picks,
            "affinity_hits": self.affinity_hits,
            "affinity_rerouted": self.affinity_rerouted,
            "pinned_tenants": len(self._affinity),
            # prefix-affinity counters (docs/PREFIX.md): repeat shared-
            # preamble traffic landing back on the replica holding its
            # blocks vs pins broken by an ineligible/stale replica
            "prefix_hits": self.prefix_hits,
            "prefix_rerouted": self.prefix_rerouted,
            "pinned_prefixes": len(self._prefix_affinity),
            # adapter-affinity counters (docs/ADAPTERS.md): traffic naming
            # a LoRA adapter landing back on the replica whose tiers
            # already hold it vs pins broken by stale/ineligible replicas
            "adapter_hits": self.adapter_hits,
            "adapter_rerouted": self.adapter_rerouted,
            "pinned_adapters": len(self._adapter_affinity),
            # circuit-breaker posture (docs/RESILIENCE.md): per-replica
            # state machines + the transition tail the autoscaler/
            # engine_top read; breaker_open_replicas is the headline
            # pressure gauge (routable capacity lost to dead pods)
            "breakers": {
                name: b.stats() for name, b in sorted(self._breakers.items())
            },
            "breaker_open_replicas": sum(
                1 for b in self._breakers.values() if b.state == OPEN
            ),
            "breaker_events": list(self.events),
            # live Retry-After holds (replica -> seconds until release)
            "held_replicas": {
                name: round(max(0.0, until - self._clock()), 3)
                for name, until in sorted(self._holds.items())
                if until > self._clock()
            },
            "holds_applied": self.holds_applied,
        }


def split_replica_target(value: str) -> tuple[str, int | None]:
    """``(base, ordinal)`` of a routing stamp: ``'app-ai-2'`` →
    ``('app-ai', 2)``, a bare ordinal ``'2'`` → ``('', 2)``, no trailing
    ordinal → ``(value, None)``. The consumer honors a stamp only when
    the base names *its own* StatefulSet (or is empty): a stamp
    targeting a sibling agent's pods must pass through untouched, or a
    two-stage pipeline would bounce every record at its second hop."""
    head, _sep, tail = value.rpartition("-")
    if tail.isdigit():
        return head, int(tail)
    return value, None
