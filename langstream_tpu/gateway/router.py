"""Replica-aware routing for serving traffic (docs/FLEET.md).

The gateway produces into a topic; with one serving replica that is the
whole story, but a fleet needs the record to land on the replica best
placed to serve it. This module is the gateway's half of that loop:

- :class:`ReplicaRouter` tracks per-replica flight snapshots (the same
  observation dicts the autoscaler consumes — queue depth, occupancy,
  health/drain posture) and picks the **least-loaded eligible** replica:
  load = ``(1 + queue depth) × (1 + occupancy/slots)``, monotone in both
  axes so a deep queue and a full batch each push traffic away.
  Draining, wedged, and unreachable replicas are never eligible — a
  record routed into a dying pod's queue is a record the drain has to
  requeue right back.
- **Session affinity** on the QoS tenant (``langstream-qos-tenant``): a
  conversation keeps hitting the replica that already holds its
  prefix-cache blocks (ROADMAP item 3's warm-TTFT lever), for as long as
  the replica stays eligible and the affinity entry is fresh. Affinity
  is advisory: an ineligible replica breaks it immediately and the
  session re-pins to the new least-loaded pick.
- **Prefix affinity** on the chained prompt-prefix digest the gateway
  stamps (``langstream-prefix-digest``, serving/prefixstore.py): repeat
  traffic for one shared system prompt lands on the replica whose
  tiered prefix store already holds its blocks — across DIFFERENT
  tenants, which tenant affinity cannot see (N tenants sharing a
  preamble is exactly the shape the prefix tiers exist for,
  docs/PREFIX.md). More specific than the tenant pin, so it is
  consulted first; prefix-less traffic takes the pre-existing path
  bit for bit.
- The choice is stamped as the ``langstream-replica`` record header; the
  serving agent's consumer honors it (``runtime/runner.py``): a replica
  that reads a record stamped for a sibling re-produces it back to the
  input topic (bounce-capped) so partition assignment and routing intent
  converge instead of fighting.

Snapshots arrive via :meth:`observe` — pushed by whoever already has
them (the control plane's autoscaler loop, a gateway-side poller, tests)
— and go stale after ``fresh_s``: routing on stale evidence is worse
than no routing, so a router with no fresh snapshot stamps nothing and
the topic's normal partition spread applies.

Stdlib-only, no locks: the router lives on the gateway's event loop;
every method is dict arithmetic (the same wait-free posture the health
plane keeps, and for the same reason — routing runs on the produce hot
path).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Callable

#: record header carrying the routing choice; the serving agent's
#: consumer honors it (see runtime/runner.py)
REPLICA_HEADER = "langstream-replica"
#: reroute loop guard: bounces a stamped record may take before the
#: consumer serves it locally anyway (better the wrong replica than a
#: record orbiting the topic after its target vanished)
BOUNCE_HEADER = "langstream-replica-bounces"
MAX_BOUNCES = 2


class ReplicaRouter:
    """Least-loaded replica choice with tenant session affinity."""

    #: max tenants pinned before LRU eviction — tenant names can be
    #: client-chosen on unauthenticated gateways (same bound the QoS
    #: limiter keeps)
    MAX_AFFINITY = 4096

    def __init__(
        self,
        fresh_s: float = 15.0,
        affinity_ttl_s: float = 600.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.fresh_s = fresh_s
        self.affinity_ttl_s = affinity_ttl_s
        self._clock = clock
        self._replicas: dict[str, dict[str, Any]] = {}
        self._observed_at: float | None = None
        # tenant -> [replica, pinned_at]
        self._affinity: "OrderedDict[str, list]" = OrderedDict()
        # prompt-prefix digest -> [replica, pinned_at] (docs/PREFIX.md):
        # bounded like the tenant map — digests derive from prompt text,
        # which clients control
        self._prefix_affinity: "OrderedDict[str, list]" = OrderedDict()
        self.picks = 0
        self.affinity_hits = 0
        self.affinity_rerouted = 0
        self.prefix_hits = 0
        self.prefix_rerouted = 0
        # disaggregated pools (docs/DISAGG.md): the phase of the latest
        # pick ("prefill"/"decode"/"any") — engine_top's split-fleet view
        self.last_pick_phase: str | None = None

    # -- snapshot ingestion ---------------------------------------------

    def observe(self, snapshots: list[dict[str, Any]]) -> None:
        """Replace the fleet view with fresh per-replica observation
        dicts (the :class:`~langstream_tpu.controlplane.autoscaler.
        ReplicaObservation` shape: ``replica``/``queued``/``occupancy``/
        ``slots``/``state``/``draining``/``unreachable``)."""
        self._replicas = {
            s["replica"]: dict(s) for s in snapshots if s.get("replica")
        }
        self._observed_at = self._clock()

    def fresh(self) -> bool:
        return (
            self._observed_at is not None
            and self._clock() - self._observed_at <= self.fresh_s
        )

    # -- choice ----------------------------------------------------------

    @staticmethod
    def _eligible(snapshot: dict[str, Any]) -> bool:
        return not (
            snapshot.get("unreachable")
            or snapshot.get("draining")
            or snapshot.get("state") == "wedged"
        )

    @staticmethod
    def _pool(snapshot: dict[str, Any]) -> str:
        return snapshot.get("pool") or "combined"

    def _pooled(self) -> bool:
        """True once any replica declares a split pool role — the
        moment phase filtering engages. Combined-only fleets never see
        it, so today's behavior stays bit-for-bit."""
        return any(
            self._pool(snap) != "combined"
            for snap in self._replicas.values()
        )

    def _phase_ok(self, snapshot: dict[str, Any], phase: str | None) -> bool:
        """Phase filter (disaggregated fleets only): new requests go to
        the prefill pool, handoffs to the decode pool; a combined
        replica in a mixed fleet serves either phase."""
        if phase is None or not self._pooled():
            return True
        pool = self._pool(snapshot)
        return pool == phase or pool == "combined"

    @staticmethod
    def _load(snapshot: dict[str, Any]) -> float:
        """(1 + queue depth) × (1 + occupancy/slots): a replica with an
        empty queue and an empty batch scores 1.0; queue growth scales
        the score linearly, batch fullness up to 2×."""
        slots = snapshot.get("slots") or 0
        occ_frac = (snapshot.get("occupancy") or 0) / slots if slots else 0.0
        return (1.0 + (snapshot.get("queued") or 0)) * (1.0 + occ_frac)

    def eligible(self) -> list[str]:
        return sorted(
            name
            for name, snap in self._replicas.items()
            if self._eligible(snap)
        )

    def pick(
        self,
        tenant: str | None = None,
        phase: str | None = None,
        exclude: Any = (),
        prefix: str | None = None,
    ) -> str | None:
        """The replica for one record: the tenant's pinned replica while
        it stays eligible and fresh, else the least-loaded eligible
        replica (ties break on name for determinism). ``None`` when the
        fleet view is stale or empty — stamp nothing, let the topic's
        partition spread route.

        ``phase`` (disaggregated fleets, docs/DISAGG.md) restricts the
        choice to that pool — ``"prefill"`` for new requests,
        ``"decode"`` for KV handoff targets; it is a no-op while every
        replica is ``combined``, so a classic fleet's routing stays
        bit-for-bit. ``exclude`` names replicas the caller already tried
        (a decode replica that answered 503 — retry the next one).

        ``prefix`` (the gateway's chained prompt-prefix digest,
        docs/PREFIX.md) pins MORE specifically than the tenant: repeat
        traffic for one shared system prompt returns to the replica
        whose prefix tiers hold its blocks, whatever tenant sent it.
        Consulted before the tenant pin; ``None`` (prefix-less traffic)
        leaves the pre-existing choice bit for bit."""
        if not self.fresh():
            return None
        exclude = set(exclude or ())
        candidates = [
            (self._load(snap), name)
            for name, snap in self._replicas.items()
            if self._eligible(snap)
            and self._phase_ok(snap, phase)
            and name not in exclude
        ]
        if not candidates:
            return None
        now = self._clock()
        self.last_pick_phase = phase or "any"
        if phase == "decode":
            # handoff targets are pure least-loaded: session affinity is
            # a prefix-cache-locality lever, and prefix blocks live on
            # the PREFILL pool — pinning decode picks under the tenant
            # would thrash the prefill pin instead
            self.picks += 1
            return min(candidates)[1]
        if prefix:
            pinned = self._prefix_affinity.get(prefix)
            if pinned is not None:
                replica, pinned_at = pinned
                snap = self._replicas.get(replica)
                if (
                    snap is not None
                    and self._eligible(snap)
                    and self._phase_ok(snap, phase)
                    and replica not in exclude
                    and now - pinned_at <= self.affinity_ttl_s
                ):
                    # the replica already holding this prompt's prefix
                    # blocks (T0/T1/T2 — docs/PREFIX.md): warm TTFT
                    # beats load spread for shared-preamble traffic
                    pinned[1] = now
                    self._prefix_affinity.move_to_end(prefix)
                    self.picks += 1
                    self.prefix_hits += 1
                    if tenant:
                        # keep the tenant pin converged on the same
                        # replica so the two affinity maps never fight
                        self._pin_tenant(tenant, replica, now)
                    return replica
                self.prefix_rerouted += 1
        if tenant:
            pinned = self._affinity.get(tenant)
            if pinned is not None:
                replica, pinned_at = pinned
                snap = self._replicas.get(replica)
                if (
                    snap is not None
                    and self._eligible(snap)
                    and self._phase_ok(snap, phase)
                    and replica not in exclude
                    and now - pinned_at <= self.affinity_ttl_s
                ):
                    # refresh the pin: an active conversation keeps its
                    # prefix-cache locality for as long as it stays warm
                    pinned[1] = now
                    self._affinity.move_to_end(tenant)
                    self.picks += 1
                    self.affinity_hits += 1
                    if prefix:
                        self._pin_prefix(prefix, replica, now)
                    return replica
                self.affinity_rerouted += 1
        choice = min(candidates)[1]
        self.picks += 1
        if tenant:
            self._pin_tenant(tenant, choice, now)
        if prefix:
            self._pin_prefix(prefix, choice, now)
        return choice

    def _pin_tenant(self, tenant: str, replica: str, now: float) -> None:
        self._affinity[tenant] = [replica, now]
        self._affinity.move_to_end(tenant)
        while len(self._affinity) > self.MAX_AFFINITY:
            self._affinity.popitem(last=False)

    def _pin_prefix(self, prefix: str, replica: str, now: float) -> None:
        self._prefix_affinity[prefix] = [replica, now]
        self._prefix_affinity.move_to_end(prefix)
        while len(self._prefix_affinity) > self.MAX_AFFINITY:
            self._prefix_affinity.popitem(last=False)

    # -- introspection ---------------------------------------------------

    def stats(self) -> dict[str, Any]:
        # per-pool eligibility census: the split-fleet view engine_top's
        # pools panel renders (combined-only fleets report one
        # "combined" row — the pre-disaggregation shape, just grouped)
        pools: dict[str, dict[str, int]] = {}
        for snap in self._replicas.values():
            entry = pools.setdefault(
                self._pool(snap), {"replicas": 0, "eligible": 0}
            )
            entry["replicas"] += 1
            if self._eligible(snap):
                entry["eligible"] += 1
        return {
            "replicas": {
                name: {
                    "load": round(self._load(snap), 3),
                    "eligible": self._eligible(snap),
                    "queued": snap.get("queued", 0),
                    "occupancy": snap.get("occupancy", 0),
                    "draining": bool(snap.get("draining")),
                    "state": snap.get("state", "ok"),
                    "unreachable": bool(snap.get("unreachable")),
                    "pool": self._pool(snap),
                }
                for name, snap in sorted(self._replicas.items())
            },
            "pools": {k: pools[k] for k in sorted(pools)},
            "last_pick_phase": self.last_pick_phase,
            "fresh": self.fresh(),
            "picks": self.picks,
            "affinity_hits": self.affinity_hits,
            "affinity_rerouted": self.affinity_rerouted,
            "pinned_tenants": len(self._affinity),
            # prefix-affinity counters (docs/PREFIX.md): repeat shared-
            # preamble traffic landing back on the replica holding its
            # blocks vs pins broken by an ineligible/stale replica
            "prefix_hits": self.prefix_hits,
            "prefix_rerouted": self.prefix_rerouted,
            "pinned_prefixes": len(self._prefix_affinity),
        }


def split_replica_target(value: str) -> tuple[str, int | None]:
    """``(base, ordinal)`` of a routing stamp: ``'app-ai-2'`` →
    ``('app-ai', 2)``, a bare ordinal ``'2'`` → ``('', 2)``, no trailing
    ordinal → ``(value, None)``. The consumer honors a stamp only when
    the base names *its own* StatefulSet (or is empty): a stamp
    targeting a sibling agent's pods must pass through untouched, or a
    two-stage pipeline would bounce every record at its second hop."""
    head, _sep, tail = value.rpartition("-")
    if tail.isdigit():
        return head, int(tail)
    return value, None
