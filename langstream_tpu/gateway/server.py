"""The gateway server (aiohttp: HTTP + WebSocket in one listener).

Endpoints (parity: ``WebSocketConfig.java:47-49``, ``GatewayResource.java``):

- WS  ``/v1/produce/{tenant}/{application}/{gateway}``
- WS  ``/v1/consume/{tenant}/{application}/{gateway}``
- WS  ``/v1/chat/{tenant}/{application}/{gateway}``
- POST ``/api/gateways/produce/{tenant}/{application}/{gateway}``
- GET  ``/api/gateways/service/{tenant}/{application}/{gateway}`` (+ POST)

Client protocol (reference-compatible shapes):
- query params: ``param:<name>=value`` for declared gateway parameters,
  ``credentials=`` for auth, ``option:position=earliest|latest`` for
  consume starting position.
- produce message: ``{"key":..., "value":..., "headers": {...}}``
- consume push:   ``{"record": {...}, "offset": "..."}``
- chat: client sends produce messages, receives consume pushes on one
  socket, correlated by the gateway's header mappings.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid
from typing import Any

from aiohttp import WSMsgType, web

from langstream_tpu.api.application import Application, Gateway
from langstream_tpu.api.record import Record, make_record
from langstream_tpu.api.topics import (
    OFFSET_HEADER,
    TopicConnectionsRuntimeRegistry,
)
from langstream_tpu.core.tracing import TRACE_HEADER, TraceContext, start_span
from langstream_tpu.gateway.auth import (
    AuthenticationException,
    get_auth_provider,
)
from langstream_tpu.gateway.router import REPLICA_HEADER, ReplicaRouter
from langstream_tpu.serving.adapters import ADAPTER_HEADER
from langstream_tpu.serving.handoff import DEADLINE_HEADER
from langstream_tpu.serving.prefixstore import (
    PREFIX_HEADER,
    prefix_digest_for_text,
)
from langstream_tpu.serving.journey import JOURNEYS
from langstream_tpu.serving.streaming import STREAMS
from langstream_tpu.serving.qos import (
    QosSpec,
    TenantLimiter,
    normalize_priority,
)

log = logging.getLogger(__name__)

#: record headers the gateway stamps so downstream AI agents hand the
#: engine the same QoS identity the gateway throttled on
QOS_TENANT_HEADER = "langstream-qos-tenant"
QOS_PRIORITY_HEADER = "langstream-qos-priority"
#: response header naming the throttled tenant on a 429
THROTTLED_HEADER = "langstream-throttled"
#: per-message stream identity stamped on streaming-flagged produces —
#: the AI agents forward it into engine options as ``stream-key``, the
#: per-chunk stream records carry it back for frame matching, and a
#: client disconnect cancels the engine future registered under it
#: (serving/streaming.py, docs/OBSERVABILITY.md Streaming)
STREAM_ID_HEADER = "langstream-stream-id"
#: header the agents' stream writer sets ``true`` on a stream's final
#: record (agents/ai.py ``_StreamWriter``)
STREAM_LAST_HEADER = "stream-last-message"


class GatewayRegistry:
    """Resolves (tenant, application, gateway-id) → (Gateway, streaming
    cluster config). Backed by the application store in the control plane,
    or by directly-registered local apps in dev mode."""

    #: the port service agents listen on in-cluster (parity: the executor
    #: service URI the reference's KubernetesApplicationStore builds)
    AGENT_SERVICE_PORT = 8790

    def __init__(self) -> None:
        self._apps: dict[tuple[str, str], Application] = {}
        self._service_uris: dict[tuple[str, str, str], str] = {}
        # per-app QoS limiter (built lazily from the app's
        # tpu-serving-configuration resource's qos section; invalidated on
        # register/unregister so a redeploy picks up new limits)
        self._qos_limiters: dict[tuple[str, str], TenantLimiter | None] = {}
        # per-app replica router (gateway/router.py): exists only once
        # someone — the control plane's autoscaler loop, a poller, tests
        # — pushes fleet snapshots via update_fleet; without fresh
        # snapshots produce paths stamp nothing and the topic's normal
        # partition spread routes
        self._routers: dict[tuple[str, str], ReplicaRouter] = {}
        # per-source (pool) fleet snapshots feeding each router, each
        # stamped with its push time: split fleets have one autoscaler
        # per pool, and the router needs the union of their latest
        # observations — with per-source aging so a removed pool's
        # replicas drop out of the merge (docs/DISAGG.md)
        self._fleet_sources: dict[
            tuple[str, str],
            dict[str, tuple[float, list[dict[str, Any]]]],
        ] = {}

    def register(self, tenant: str, app_id: str, application: Application) -> None:
        self._apps[(tenant, app_id)] = application
        self._qos_limiters.pop((tenant, app_id), None)

    def application(self, tenant: str, app_id: str) -> Application | None:
        return self._apps.get((tenant, app_id))

    def unregister(self, tenant: str, app_id: str) -> None:
        self._apps.pop((tenant, app_id), None)
        self._qos_limiters.pop((tenant, app_id), None)
        self._routers.pop((tenant, app_id), None)
        self._fleet_sources.pop((tenant, app_id), None)
        for key in [k for k in self._service_uris if k[:2] == (tenant, app_id)]:
            del self._service_uris[key]

    def update_fleet(
        self,
        tenant: str,
        app_id: str,
        snapshots: list[dict[str, Any]],
        source: str = "",
    ) -> None:
        """Feed the app's router fresh per-replica observations (the
        autoscaler's observe() output — it already fans in exactly the
        evidence routing needs, so the two consume one snapshot).
        ``source`` names the feeding pool for disaggregated fleets
        (docs/DISAGG.md): each pool's autoscaler observes only its own
        StatefulSet, so the router's view is the union of the latest
        snapshot from EVERY source — one pool's push must not evict the
        other pool's replicas. Each source's contribution carries its
        own freshness: a source that stops pushing (a pool removed on
        redeploy, a dead autoscaler loop) ages out of the merge within
        the router's freshness window instead of keeping ghost replicas
        routable forever just because a sibling source stays live."""
        key = (tenant, app_id)
        router = self._routers.setdefault(key, ReplicaRouter())
        now = time.monotonic()
        sources = self._fleet_sources.setdefault(key, {})
        sources[source] = (now, list(snapshots))
        for stale in [
            s
            for s, (stamped, _) in sources.items()
            if now - stamped > router.fresh_s
        ]:
            del sources[stale]
        merged = [
            snap for _, chunk in sources.values() for snap in chunk
        ]
        router.observe(merged)

    def router(self, tenant: str, app_id: str) -> ReplicaRouter | None:
        return self._routers.get((tenant, app_id))

    def route_replica(
        self,
        tenant: str,
        app_id: str,
        qos_tenant: str | None,
        prefix: str | None = None,
        adapter: str | None = None,
    ) -> str | None:
        """The replica one produced record should land on (None = don't
        stamp): least-loaded eligible member, with session affinity on
        the QoS tenant so a conversation keeps its prefix-cache blocks,
        and — more specifically — prefix affinity on the stamped
        prompt-prefix digest so shared-preamble traffic from ANY tenant
        returns to the replica whose prefix tiers hold its blocks
        (docs/PREFIX.md). Gateway-produced records are NEW requests, so
        a disaggregated fleet routes them to the prefill pool (phase
        filtering is a no-op while every replica is combined —
        docs/DISAGG.md)."""
        router = self._routers.get((tenant, app_id))
        if router is None:
            return None
        return router.pick(
            qos_tenant, phase="prefill", prefix=prefix, adapter=adapter
        )

    def qos_limiter(self, tenant: str, app_id: str) -> TenantLimiter | None:
        """The app's gateway-side QoS limiter (None when the app declares
        no enabled qos section). The same :class:`QosSpec` the engine
        enforces — buckets are enforced at BOTH ends: the gateway sheds
        before a record ever enters the broker, the engine backstops
        produce paths that bypass the gateway."""
        key = (tenant, app_id)
        if key not in self._qos_limiters:
            limiter = None
            app = self._apps.get(key)
            for res in (getattr(app, "resources", None) or {}).values():
                if getattr(res, "type", None) != "tpu-serving-configuration":
                    continue
                try:
                    spec = QosSpec.from_dict(
                        (res.configuration or {}).get("qos")
                    )
                except ValueError as e:
                    # deploy validation rejects malformed specs; a stale
                    # app that slipped through must not break produce
                    log.warning("ignoring invalid qos section: %s", e)
                    continue
                if spec is not None and spec.enabled:
                    limiter = TenantLimiter(spec)
                    break
            self._qos_limiters[key] = limiter
        return self._qos_limiters[key]

    def register_service_uri(
        self, tenant: str, app_id: str, agent_id: str, uri: str
    ) -> None:
        """Dev-mode/in-process agents register where they listen; in-cluster
        the naming-convention fallback below needs no registration."""
        self._service_uris[(tenant, app_id, agent_id)] = uri.rstrip("/")

    def service_uri(self, tenant: str, app_id: str, agent_id: str) -> str:
        explicit = self._service_uris.get((tenant, app_id, agent_id))
        if explicit:
            return explicit
        # k8s: the agent's headless service lives in the TENANT namespace
        # (cluster_runtime.tenant_namespace), not the gateway's own — the
        # qualified name is what resolves from the gateway pod. The port is
        # the agent's own declared service-port (a headless service resolves
        # to pod IPs, so the declared Service ports don't constrain it);
        # AGENT_SERVICE_PORT is only the convention-default.
        port = self.AGENT_SERVICE_PORT
        app = self._apps.get((tenant, app_id))
        if app is not None:
            for agent in app.all_agents():
                if agent.id == agent_id:
                    port = int(
                        (agent.configuration or {}).get("service-port", port)
                    )
                    break
        name = f"{app_id}-{agent_id}".lower().replace("_", "-")
        namespace = f"langstream-{tenant}".lower()
        return f"http://{name}.{namespace}.svc:{port}"

    def resolve(
        self, tenant: str, app_id: str, gateway_id: str
    ) -> tuple[Gateway, dict[str, Any]]:
        app = self._apps.get((tenant, app_id))
        if app is None:
            raise web.HTTPNotFound(reason=f"unknown application {tenant}/{app_id}")
        for gw in app.gateways:
            if gw.id == gateway_id:
                streaming = app.instance.streaming_cluster
                return gw, {
                    "type": streaming.type,
                    "configuration": streaming.configuration,
                }
        raise web.HTTPNotFound(reason=f"unknown gateway {gateway_id!r}")


class GatewayServer:
    def __init__(self, registry: GatewayRegistry | None = None, port: int = 8091,
                 host: str = "127.0.0.1"):
        self.registry = registry or GatewayRegistry()
        self.port = port
        self.host = host
        self.app = web.Application()
        self.app.add_routes(
            [
                web.get("/v1/produce/{tenant}/{application}/{gateway}", self._ws_produce),
                web.get("/v1/consume/{tenant}/{application}/{gateway}", self._ws_consume),
                web.get("/v1/chat/{tenant}/{application}/{gateway}", self._ws_chat),
                web.post(
                    "/api/gateways/produce/{tenant}/{application}/{gateway}",
                    self._http_produce,
                ),
                web.route(
                    "*",
                    "/api/gateways/service/{tenant}/{application}/{gateway}",
                    self._http_service,
                ),
                web.route(
                    "*",
                    "/api/gateways/service/{tenant}/{application}/{gateway}/{tail:.*}",
                    self._http_service,
                ),
            ]
        )
        self._runner: web.AppRunner | None = None
        # per-QoS-tenant throttle counters (lazily created: tenants are
        # client identities, unknown until the first 429)
        self._m_throttled: dict[str, Any] = {}

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        log.info("gateway listening on :%d", self.port)

    async def stop(self) -> None:
        proxy_client = getattr(self, "_proxy_client", None)
        if proxy_client is not None and not proxy_client.closed:
            await proxy_client.close()
        if self._runner is not None:
            await self._runner.cleanup()

    # ------------------------------------------------------------------
    # shared plumbing
    # ------------------------------------------------------------------

    def _context(self, request: web.Request):
        tenant = request.match_info["tenant"]
        app_id = request.match_info["application"]
        gateway_id = request.match_info["gateway"]
        gateway, streaming = self.registry.resolve(tenant, app_id, gateway_id)
        params: dict[str, str] = {}
        options: dict[str, str] = {}
        for k, v in request.query.items():
            if k.startswith("param:"):
                params[k[6:]] = v
            elif k.startswith("option:"):
                options[k[7:]] = v
        missing = [p for p in gateway.parameters if p not in params]
        if missing:
            raise web.HTTPBadRequest(reason=f"missing parameters: {missing}")
        credentials = request.query.get("credentials")
        return tenant, app_id, gateway, streaming, params, options, credentials

    async def _authenticate(
        self, gateway: Gateway, credentials: str | None
    ) -> dict[str, Any]:
        if not gateway.authentication:
            return {}
        provider = get_auth_provider(
            gateway.authentication.get("provider", "test"),
            gateway.authentication.get("configuration", {}),
        )
        try:
            return await provider.authenticate(credentials)
        except AuthenticationException:
            raise
        except Exception as e:
            # provider infrastructure failure (endpoint down, bad config):
            # an auth failure to the client, not a 500 with a traceback
            log.warning("auth provider failure: %s", e)
            raise AuthenticationException(f"authentication unavailable: {e}")

    @staticmethod
    def _mapped_headers(
        mappings, params: dict[str, str], principal: dict[str, Any]
    ) -> dict[str, Any]:
        headers: dict[str, Any] = {}
        for m in mappings:
            if m.value_from_parameters:
                value = params.get(m.value_from_parameters)
            elif m.value_from_authentication:
                value = principal.get(m.value_from_authentication)
            else:
                value = m.literal_value
            key = m.key or (
                f"langstream-client-{m.value_from_parameters or m.value_from_authentication}"
            )
            if value is not None:
                headers[key] = value
        return headers

    @staticmethod
    async def _json_body(request: web.Request) -> dict[str, Any]:
        """Parse a JSON object body; malformed input is a client error (400),
        not a front-door 500."""
        try:
            payload = await request.json()
        except (json.JSONDecodeError, UnicodeDecodeError):
            raise web.HTTPBadRequest(reason="body is not valid JSON")
        if not isinstance(payload, dict):
            raise web.HTTPBadRequest(reason="body must be a JSON object")
        return payload

    @staticmethod
    def _record_json(record: Record) -> dict[str, Any]:
        offset = None
        headers = {}
        for k, v in record.headers:
            if k == OFFSET_HEADER:
                offset = f"{v.topic}:{v.partition}:{v.offset}"
            else:
                headers[k] = v
        return {
            "record": {"key": record.key, "value": record.value, "headers": headers},
            "offset": offset,
        }

    @staticmethod
    def _traced_headers(
        headers: dict[str, Any], span_name: str
    ) -> tuple[dict[str, Any], Any]:
        """Open the gateway-side span for one produced record and stamp its
        context into the record headers (honoring a client-supplied
        ``langstream-trace`` traceparent as the parent). Returns
        ``(headers, span)``; the header value is echoed back to the client
        so it can fetch ``/traces/<trace_id>`` afterwards."""
        span = start_span(
            span_name, service="gateway", parent=headers.get(TRACE_HEADER)
        )
        headers = dict(headers)
        headers[TRACE_HEADER] = span.context().to_header()
        return headers, span

    # ------------------------------------------------------------------
    # QoS: tenant identity + gateway-side throttling
    # ------------------------------------------------------------------

    @staticmethod
    def _qos_identity(
        params: dict[str, str], principal: dict[str, Any]
    ) -> tuple[str, str]:
        """(qos tenant, priority class) for one client: the authenticated
        subject is the tenant when auth is on (clients cannot spoof it);
        an explicit ``param:tenant`` covers unauthenticated dev setups.
        Priority comes from ``param:priority``, clamped to a known class."""
        tenant = str(
            principal.get("subject") or params.get("tenant") or "anonymous"
        )
        return tenant, normalize_priority(params.get("priority"))

    def _qos_headers(
        self,
        limiter: TenantLimiter | None,
        params: dict[str, str],
        principal: dict[str, Any],
    ) -> dict[str, str]:
        """Record headers carrying the QoS identity downstream (the AI
        agents forward them into engine options, so the engine's own
        buckets and priority classes see the same tenant the gateway
        throttled). Stamped only when the app has QoS configured or the
        client asked for special treatment — otherwise record headers
        stay byte-identical to the pre-QoS gateway."""
        if (
            limiter is None
            and "tenant" not in params
            and "priority" not in params
        ):
            return {}
        tenant, priority = self._qos_identity(params, principal)
        out = {QOS_TENANT_HEADER: tenant, QOS_PRIORITY_HEADER: priority}
        if limiter is not None:
            # tenant config names the LoRA adapter this tenant decodes
            # with (docs/ADAPTERS.md): stamped here so the agents, the
            # engine, and the router all see the SAME adapter identity
            # the gateway resolved — clients cannot steer themselves
            # onto another tenant's fine-tune
            policy = limiter.spec.tenant_policy(tenant)
            if policy is not None and policy.adapter:
                out[ADAPTER_HEADER] = policy.adapter
        return out

    def _stamp_deadline(
        self,
        headers: dict[str, Any],
        limiter: TenantLimiter | None,
        params: dict[str, str],
        priority: str,
    ) -> dict[str, Any]:
        """Stamp the record's end-to-end deadline (in place):
        ``langstream-deadline`` = absolute epoch seconds, enforced
        504-shaped by every engine on the request's path (serving/
        handoff.py, docs/RESILIENCE.md). A client-supplied header wins;
        a ``deadline-s`` query param is a client-relative budget; and an
        app whose qos section opts in (``deadline-headers: true``) gets
        the per-class default stamped on everything else. No deadline
        anywhere → headers stay byte-identical (the default-config
        pin)."""
        if headers.get(DEADLINE_HEADER):
            return headers  # explicit client budget: honored end to end
        raw = params.get("deadline-s")
        if raw is not None:
            try:
                headers[DEADLINE_HEADER] = repr(
                    time.time() + max(0.0, float(raw))
                )
            except (TypeError, ValueError):
                pass  # malformed param degrades to "no deadline"
            return headers
        if limiter is not None and limiter.spec.deadline_headers:
            headers[DEADLINE_HEADER] = repr(
                time.time() + limiter.spec.class_policy(priority).deadline_s
            )
        return headers

    def _stamp_replica(
        self,
        headers: dict[str, Any],
        tenant: str,
        app_id: str,
        params: dict[str, Any],
        principal: dict[str, Any],
        value: Any = None,
    ) -> dict[str, Any]:
        """Stamp the routing choice onto one produced record (in place).
        Per-message, not per-connection: load shifts and affinity pins
        between messages on one WebSocket. The affinity key is the SAME
        QoS identity the limiter throttled on (resolved here from the
        same params/principal so the two can never disagree) — except
        that the shared ``anonymous`` fallback gets no affinity pin:
        every unauthenticated client shares that name, and pinning it
        would funnel all anonymous traffic onto one replica, defeating
        least-loaded routing exactly in the common dev/bench setup. A
        client-supplied stamp is honored — explicit targeting (debug,
        pinned benchmarks) beats the router's heuristic.

        ``value`` is the record's prompt payload: when it is long
        enough, its chained prefix digest is stamped as the
        ``langstream-prefix-digest`` header and routes by prefix
        affinity — N tenants sharing one system prompt converge on the
        replica whose prefix tiers hold its blocks (docs/PREFIX.md).
        Short or absent values stamp nothing and route exactly as
        before."""
        prefix = prefix_digest_for_text(value)
        if prefix is not None and PREFIX_HEADER not in headers:
            headers[PREFIX_HEADER] = prefix
        if REPLICA_HEADER in headers:
            return headers
        qos_tenant, _ = self._qos_identity(params, principal)
        affinity = qos_tenant if qos_tenant != "anonymous" else None
        # adapter identity was already injected from tenant config (or a
        # client header on adapter-permissive setups): route by adapter
        # affinity beside the prefix pins (docs/ADAPTERS.md)
        adapter = headers.get(ADAPTER_HEADER) or None
        if prefix is not None or adapter is not None:
            replica = self.registry.route_replica(
                tenant, app_id, affinity, prefix=prefix, adapter=adapter
            )
        else:
            # prefix-less traffic keeps the pre-tier call shape exactly
            replica = self.registry.route_replica(tenant, app_id, affinity)
        if replica is not None:
            headers[REPLICA_HEADER] = replica
        return headers

    @staticmethod
    def _journey_produce(headers: dict[str, Any]) -> None:
        """Record the gateway-side journey edge (serving/journey.py) for
        one ADMITTED produce, keyed by the trace id stamped into the
        record — the engine's submit/admit edges chain onto it, so the
        gateway→engine gap ("ingest": broker + agent hop) becomes a
        named TTFT segment. Called only after the QoS gate admits the
        message: a throttled request never entered the system, and a
        burst of 429s must not FIFO-evict live journeys from the
        bounded ledger."""
        ctx = TraceContext.parse(headers.get(TRACE_HEADER))
        if ctx is not None:
            JOURNEYS.record(
                ctx.trace_id, "gateway-produce",
                replica=headers.get(REPLICA_HEADER),
            )

    #: max distinct tenant labels on the throttle counter — tenant names
    #: can be client-chosen on unauthenticated gateways, and Prometheus
    #: label cardinality (and this dict) must not grow with them
    _MAX_THROTTLE_LABELS = 256

    def _count_throttle(self, tenant: str) -> None:
        if (
            tenant not in self._m_throttled
            and len(self._m_throttled) >= self._MAX_THROTTLE_LABELS
        ):
            tenant = "<other>"
        counter = self._m_throttled.get(tenant)
        if counter is None:
            from langstream_tpu.api.metrics import PrometheusMetricsReporter

            counter = PrometheusMetricsReporter(
                prefix="langstream_gateway", agent_id=tenant
            ).counter(
                "throttled_total",
                "produce requests refused with 429 for this QoS tenant",
            )
            self._m_throttled[tenant] = counter
        counter(1)

    @staticmethod
    def _retry_after_header(retry: float) -> str:
        # Retry-After is integral seconds; round UP so a client honoring
        # it never retries into a still-empty bucket
        return str(max(1, -(-int(retry * 1000) // 1000)))

    def _throttle_http(
        self, tenant: str, retry: float, trace: str | None = None
    ) -> web.Response:
        """Structured 429: machine-readable body + ``Retry-After`` +
        ``langstream-throttled`` naming the tenant (so a shared proxy can
        tell whose budget was hit) + the trace header when a span was
        already opened for the rejected produce."""
        self._count_throttle(tenant)
        headers = {
            "Retry-After": self._retry_after_header(retry),
            THROTTLED_HEADER: tenant,
        }
        body: dict[str, Any] = {
            "status": "THROTTLED",
            "reason": f"tenant {tenant!r} over its rate limit",
            "retry-after": round(retry, 3),
        }
        if trace:
            headers[TRACE_HEADER] = trace
            body["trace"] = trace
        return web.json_response(body, status=429, headers=headers)

    def _ws_throttle_gate(
        self, limiter: TenantLimiter | None, tenant: str
    ) -> None:
        """WS upgrade gate: a tenant whose bucket is already empty gets
        the 429 at the handshake (read-only peek — the upgrade itself
        costs no budget; per-message debits happen on each produce)."""
        if limiter is None:
            return
        retry = limiter.retry_after(tenant)
        if retry is not None:
            self._count_throttle(tenant)
            raise web.HTTPTooManyRequests(
                reason=f"tenant {tenant!r} over its rate limit",
                headers={
                    "Retry-After": self._retry_after_header(retry),
                    THROTTLED_HEADER: tenant,
                },
            )

    def _filters_match(
        self, gateway: Gateway, params, principal, record: Record
    ) -> bool:
        expected = self._mapped_headers(gateway.consume_filters, params, principal)
        record_headers = record.header_map()
        return all(record_headers.get(k) == v for k, v in expected.items())

    async def _emit_event(self, gateway: Gateway, streaming, event_type: str,
                          tenant: str, app_id: str) -> None:
        """Client lifecycle events (parity: ``EventRecord.java:29-44``)."""
        if not gateway.events_topic:
            return
        try:
            runtime = TopicConnectionsRuntimeRegistry.get_runtime(streaming)
            producer = runtime.create_producer("gateway-events", {"topic": gateway.events_topic})
            await producer.start()
            await producer.write(
                make_record(
                    value={
                        "type": event_type,
                        "tenant": tenant,
                        "application": app_id,
                        "gateway": gateway.id,
                    }
                )
            )
            await producer.close()
            await runtime.close()
        except Exception:
            log.exception("failed to emit gateway event")

    # ------------------------------------------------------------------
    # produce
    # ------------------------------------------------------------------

    async def _ws_produce(self, request: web.Request) -> web.WebSocketResponse:
        tenant, app_id, gateway, streaming, params, options, credentials = (
            self._context(request)
        )
        if gateway.type != Gateway.PRODUCE:
            raise web.HTTPBadRequest(reason="not a produce gateway")
        try:
            principal = await self._authenticate(gateway, credentials)
        except AuthenticationException as e:
            raise web.HTTPUnauthorized(reason=str(e))
        limiter = self.registry.qos_limiter(tenant, app_id)
        qos_tenant, qos_priority = self._qos_identity(params, principal)
        # an already-empty bucket refuses the upgrade itself with a real
        # 429 (per-message throttling below covers mid-stream exhaustion)
        self._ws_throttle_gate(limiter, qos_tenant)
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        await self._emit_event(gateway, streaming, "ClientConnected", tenant, app_id)
        runtime = TopicConnectionsRuntimeRegistry.get_runtime(streaming)
        producer = runtime.create_producer("gateway-produce", {"topic": gateway.topic})
        await producer.start()
        stream_on = (
            self._stream_requested(options) and gateway.stream_topic is not None
        )
        active_streams: set[str] = set()
        stream_reader = None
        stream_pusher = None
        if stream_on:
            # the chunk reader goes live BEFORE any produce is accepted:
            # started after a write, it could miss the first frames of a
            # fast stream (read position is `latest`)
            stream_reader = runtime.create_reader(
                {"topic": gateway.stream_topic}, initial_position="latest"
            )
            await stream_reader.start()
            stream_pusher = asyncio.ensure_future(
                self._stream_push_loop(ws, stream_reader, active_streams)
            )
        inject = {
            **self._mapped_headers(gateway.produce_headers, params, principal),
            **self._qos_headers(limiter, params, principal),
        }
        try:
            async for msg in ws:
                if msg.type != WSMsgType.TEXT:
                    continue
                try:
                    payload = json.loads(msg.data)
                    headers, span = self._traced_headers(
                        {**(payload.get("headers") or {}), **inject},
                        "gateway.produce",
                    )
                    self._stamp_replica(
                        headers, tenant, app_id, params, principal,
                        value=payload.get("value"),
                    )
                    self._stamp_deadline(
                        headers, limiter, params, qos_priority
                    )
                    retry = (
                        limiter.admit_request(qos_tenant)
                        if limiter is not None
                        else None
                    )
                    if retry is not None:
                        # the span records the rejection (error label),
                        # and the structured ack mirrors the HTTP 429
                        span.end(error="throttled")
                        self._count_throttle(qos_tenant)
                        await ws.send_json(
                            {
                                "status": "THROTTLED",
                                "reason": f"tenant {qos_tenant!r} over its "
                                          f"rate limit",
                                "retry-after": round(retry, 3),
                                "trace": headers[TRACE_HEADER],
                            }
                        )
                        continue
                    stream_id = None
                    if stream_on:
                        # per-message, not per-connection: one socket
                        # can carry many concurrent streams, each its
                        # own engine-side cancellation handle
                        stream_id = str(uuid.uuid4())
                        headers[STREAM_ID_HEADER] = stream_id
                        active_streams.add(stream_id)
                    self._journey_produce(headers)
                    record = make_record(
                        value=payload.get("value"),
                        key=payload.get("key"),
                        headers=headers,
                    )
                    with span:
                        await producer.write(record)
                    ack = {"status": "OK", "trace": headers[TRACE_HEADER]}
                    if stream_id is not None:
                        ack["stream-id"] = stream_id
                    await ws.send_json(ack)
                except Exception as e:
                    await ws.send_json({"status": "BAD_REQUEST", "reason": str(e)})
        finally:
            if stream_pusher is not None:
                stream_pusher.cancel()
            if stream_reader is not None:
                await stream_reader.close()
            for sid in active_streams:
                # disconnect IS cancellation: cancel the engine future
                # registered under each still-open stream so the decode
                # slot frees at the next chunk boundary (a completed
                # stream already left the registry — no-op)
                STREAMS.cancel(sid)
            await producer.close()
            await runtime.close()
            await self._emit_event(
                gateway, streaming, "ClientDisconnected", tenant, app_id
            )
        return ws

    @staticmethod
    def _stream_requested(options: dict[str, str]) -> bool:
        """``option:streaming`` truthiness (query options are strings)."""
        return str(options.get("streaming", "")).lower() in (
            "1", "true", "yes", "on",
        )

    async def _stream_push_loop(self, ws, reader, active: set) -> None:
        """Forward per-chunk stream records to one streaming-flagged
        produce socket. Frame-writer discipline (graftcheck STRM1501):
        the loop body is reads, header matches, and frame writes only —
        no locks, no blocking I/O, no host syncs — because every stall
        here lands directly in the client's time-between-tokens."""
        try:
            while not ws.closed:
                records = await reader.read(timeout=0.5)
                for record in records:
                    headers = record.header_map()
                    sid = headers.get(STREAM_ID_HEADER)
                    if sid is None or sid not in active:
                        continue
                    await ws.send_json(self._record_json(record))
                    if str(headers.get(STREAM_LAST_HEADER)).lower() == "true":
                        # completed stream: nothing to cancel on
                        # disconnect anymore
                        active.discard(sid)
        except (asyncio.CancelledError, ConnectionResetError):
            pass
        except Exception:
            log.exception("stream push loop failed")

    async def _http_produce(self, request: web.Request) -> web.Response:
        tenant, app_id, gateway, streaming, params, options, credentials = (
            self._context(request)
        )
        if gateway.type != Gateway.PRODUCE:
            raise web.HTTPBadRequest(reason="not a produce gateway")
        try:
            principal = await self._authenticate(gateway, credentials)
        except AuthenticationException as e:
            raise web.HTTPUnauthorized(reason=str(e))
        payload = await self._json_body(request)
        limiter = self.registry.qos_limiter(tenant, app_id)
        qos_tenant, qos_priority = self._qos_identity(params, principal)
        inject = {
            **self._mapped_headers(gateway.produce_headers, params, principal),
            **self._qos_headers(limiter, params, principal),
        }
        headers, span = self._traced_headers(
            {**(payload.get("headers") or {}), **inject}, "gateway.produce"
        )
        self._stamp_replica(
            headers, tenant, app_id, params, principal,
            value=payload.get("value"),
        )
        self._stamp_deadline(headers, limiter, params, qos_priority)
        if limiter is not None:
            retry = limiter.admit_request(qos_tenant)
            if retry is not None:
                span.end(error="throttled")
                return self._throttle_http(
                    qos_tenant, retry, headers[TRACE_HEADER]
                )
        self._journey_produce(headers)
        runtime = TopicConnectionsRuntimeRegistry.get_runtime(streaming)
        if self._stream_requested(options) and gateway.stream_topic is not None:
            # SSE variant: hold the response open and deliver each chunk
            # record as a `data:` frame (closes the runtime itself)
            return await self._sse_produce(
                request, gateway, runtime, payload, headers, span
            )
        producer = runtime.create_producer("gateway-produce", {"topic": gateway.topic})
        await producer.start()
        try:
            with span:
                await producer.write(
                    make_record(
                        value=payload.get("value"),
                        key=payload.get("key"),
                        headers=headers,
                    )
                )
        finally:
            await producer.close()
            await runtime.close()
        return web.json_response(
            {"status": "OK", "trace": headers[TRACE_HEADER]},
            headers={TRACE_HEADER: headers[TRACE_HEADER]},
        )

    async def _sse_produce(
        self,
        request: web.Request,
        gateway: Gateway,
        runtime,
        payload: dict[str, Any],
        headers: dict[str, Any],
        span,
    ) -> web.StreamResponse:
        """The SSE variant of the HTTP produce route: one POST with
        ``option:streaming=true`` against a stream-topic gateway holds
        the response open (``text/event-stream``) and delivers each
        chunk record as a ``data:`` frame. Heartbeat comments go out on
        idle polls so a gone client surfaces as a write failure — which
        maps to cancellation of the engine future, exactly like a WS
        disconnect. Frame-writer discipline applies (graftcheck
        STRM1501): the delivery loop is reads and frame writes only."""
        stream_id = str(uuid.uuid4())
        headers[STREAM_ID_HEADER] = stream_id
        response = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                TRACE_HEADER: headers[TRACE_HEADER],
                STREAM_ID_HEADER: stream_id,
            },
        )
        await response.prepare(request)
        # the chunk reader goes live BEFORE the produce: started after,
        # it could miss the first frames of a fast stream (`latest`)
        reader = runtime.create_reader(
            {"topic": gateway.stream_topic}, initial_position="latest"
        )
        await reader.start()
        producer = runtime.create_producer(
            "gateway-produce", {"topic": gateway.topic}
        )
        await producer.start()
        try:
            with span:
                await producer.write(
                    make_record(
                        value=payload.get("value"),
                        key=payload.get("key"),
                        headers=headers,
                    )
                )
            done = False
            while not done:
                records = await reader.read(timeout=0.5)
                if not records:
                    # comment frame: keeps intermediaries from timing
                    # the idle stream out AND probes the socket — a dead
                    # client raises here instead of leaking the slot
                    await response.write(b": keep-alive\n\n")
                    continue
                for record in records:
                    rec_headers = record.header_map()
                    if rec_headers.get(STREAM_ID_HEADER) != stream_id:
                        continue
                    frame = json.dumps(self._record_json(record))
                    await response.write(f"data: {frame}\n\n".encode())
                    if str(rec_headers.get(STREAM_LAST_HEADER)).lower() == "true":
                        done = True
        except asyncio.CancelledError:
            # aiohttp cancels the handler on client disconnect:
            # disconnect IS cancellation (no-op for a finished stream)
            STREAMS.cancel(stream_id)
            raise
        except ConnectionResetError:
            STREAMS.cancel(stream_id)
        finally:
            await producer.close()
            await reader.close()
            await runtime.close()
        try:
            await response.write_eof()
        except ConnectionResetError:
            pass
        return response

    # ------------------------------------------------------------------
    # consume
    # ------------------------------------------------------------------

    async def _ws_consume(self, request: web.Request) -> web.WebSocketResponse:
        tenant, app_id, gateway, streaming, params, options, credentials = (
            self._context(request)
        )
        if gateway.type != Gateway.CONSUME:
            raise web.HTTPBadRequest(reason="not a consume gateway")
        try:
            principal = await self._authenticate(gateway, credentials)
        except AuthenticationException as e:
            raise web.HTTPUnauthorized(reason=str(e))
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        await self._emit_event(gateway, streaming, "ClientConnected", tenant, app_id)
        runtime = TopicConnectionsRuntimeRegistry.get_runtime(streaming)
        reader = runtime.create_reader(
            {"topic": gateway.topic},
            initial_position=options.get("position", "latest"),
        )
        await reader.start()
        pusher = asyncio.ensure_future(
            self._push_loop(ws, reader, gateway, params, principal)
        )
        try:
            async for msg in ws:
                if msg.type == WSMsgType.TEXT:
                    pass  # client acks are accepted and ignored (at-most-once push)
        finally:
            pusher.cancel()
            await reader.close()
            await runtime.close()
            await self._emit_event(
                gateway, streaming, "ClientDisconnected", tenant, app_id
            )
        return ws

    async def _push_loop(self, ws, reader, gateway, params, principal) -> None:
        try:
            while not ws.closed:
                records = await reader.read(timeout=0.5)
                for record in records:
                    if self._filters_match(gateway, params, principal, record):
                        await ws.send_json(self._record_json(record))
        except (asyncio.CancelledError, ConnectionResetError):
            pass
        except Exception:
            log.exception("consume push loop failed")

    # ------------------------------------------------------------------
    # chat: produce + consume on one socket
    # ------------------------------------------------------------------

    async def _ws_chat(self, request: web.Request) -> web.WebSocketResponse:
        tenant, app_id, gateway, streaming, params, options, credentials = (
            self._context(request)
        )
        if gateway.type != Gateway.CHAT:
            raise web.HTTPBadRequest(reason="not a chat gateway")
        try:
            principal = await self._authenticate(gateway, credentials)
        except AuthenticationException as e:
            raise web.HTTPUnauthorized(reason=str(e))
        chat = gateway.chat_options
        questions_topic = chat.get("questions-topic")
        answers_topic = chat.get("answers-topic")
        if not questions_topic or not answers_topic:
            raise web.HTTPBadRequest(reason="chat gateway needs questions/answers topics")
        limiter = self.registry.qos_limiter(tenant, app_id)
        qos_tenant, qos_priority = self._qos_identity(params, principal)
        self._ws_throttle_gate(limiter, qos_tenant)
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        await self._emit_event(gateway, streaming, "ClientConnected", tenant, app_id)
        runtime = TopicConnectionsRuntimeRegistry.get_runtime(streaming)
        producer = runtime.create_producer("gateway-chat", {"topic": questions_topic})
        await producer.start()
        reader = runtime.create_reader(
            {"topic": answers_topic}, initial_position="latest"
        )
        await reader.start()
        inject = {
            **self._mapped_headers(gateway.produce_headers, params, principal),
            **self._qos_headers(limiter, params, principal),
        }
        # streaming-flagged chat sockets get per-message stream ids: the
        # answers topic already carries the agent's chunk records back
        # (headers copy through the stream writer), so frames need no
        # extra reader — the id exists for disconnect-as-cancellation
        chat_stream = self._stream_requested(options)
        active_streams: set[str] = set()
        # the same headers injected on produce are the consume-side filters
        # (that's how chat correlates answers to this session)
        pusher = asyncio.ensure_future(
            self._chat_push_loop(ws, reader, inject, active_streams)
        )
        try:
            async for msg in ws:
                if msg.type != WSMsgType.TEXT:
                    continue
                try:
                    payload = json.loads(msg.data)
                    headers, span = self._traced_headers(
                        {**(payload.get("headers") or {}), **inject},
                        "gateway.chat",
                    )
                    self._stamp_replica(
                        headers, tenant, app_id, params, principal,
                        value=payload.get("value"),
                    )
                    self._stamp_deadline(
                        headers, limiter, params, qos_priority
                    )
                    retry = (
                        limiter.admit_request(qos_tenant)
                        if limiter is not None
                        else None
                    )
                    if retry is not None:
                        span.end(error="throttled")
                        self._count_throttle(qos_tenant)
                        await ws.send_json(
                            {
                                "status": "THROTTLED",
                                "reason": f"tenant {qos_tenant!r} over its "
                                          f"rate limit",
                                "retry-after": round(retry, 3),
                                "trace": headers[TRACE_HEADER],
                            }
                        )
                        continue
                    stream_id = None
                    if chat_stream:
                        stream_id = str(uuid.uuid4())
                        headers[STREAM_ID_HEADER] = stream_id
                        active_streams.add(stream_id)
                    self._journey_produce(headers)
                    with span:
                        await producer.write(
                            make_record(
                                value=payload.get("value"),
                                key=payload.get("key"),
                                headers=headers,
                            )
                        )
                    ack = {"status": "OK", "trace": headers[TRACE_HEADER]}
                    if stream_id is not None:
                        ack["stream-id"] = stream_id
                    await ws.send_json(ack)
                except Exception as e:
                    await ws.send_json({"status": "BAD_REQUEST", "reason": str(e)})
        finally:
            pusher.cancel()
            for sid in active_streams:
                # disconnect IS cancellation: free the decode slot of
                # every stream still open on this socket (no-op for
                # completed streams — they left the registry)
                STREAMS.cancel(sid)
            await producer.close()
            await reader.close()
            await runtime.close()
            await self._emit_event(
                gateway, streaming, "ClientDisconnected", tenant, app_id
            )
        return ws

    async def _chat_push_loop(
        self,
        ws,
        reader,
        inject: dict[str, Any],
        active: set | None = None,
    ) -> None:
        try:
            while not ws.closed:
                records = await reader.read(timeout=0.5)
                for record in records:
                    headers = record.header_map()
                    if all(headers.get(k) == v for k, v in inject.items()):
                        await ws.send_json(self._record_json(record))
                        if (
                            active
                            and str(headers.get(STREAM_LAST_HEADER)).lower()
                            == "true"
                        ):
                            # completed stream: drop its cancel handle
                            active.discard(headers.get(STREAM_ID_HEADER))
        except (asyncio.CancelledError, ConnectionResetError):
            pass
        except Exception:
            log.exception("chat push loop failed")

    # ------------------------------------------------------------------
    # service gateway: agent proxy
    # ------------------------------------------------------------------

    _HOP_HEADERS = {
        "connection", "keep-alive", "proxy-authenticate",
        "proxy-authorization", "te", "trailers", "transfer-encoding",
        "upgrade", "host", "content-length",
        # aiohttp auto-decompresses upstream bodies, so forwarding the
        # upstream Content-Encoding would declare an encoding the payload
        # no longer has
        "content-encoding",
    }

    async def _proxy_session(self):
        """One shared upstream session (connection pooling on the proxy hot
        path); closed in :meth:`stop`."""
        import aiohttp

        if getattr(self, "_proxy_client", None) is None or self._proxy_client.closed:
            self._proxy_client = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=60)
            )
        return self._proxy_client

    async def _proxy_to_agent(
        self, request: web.Request, tenant: str, app_id: str, agent_id: str
    ) -> web.Response:
        import aiohttp

        base = self.registry.service_uri(tenant, app_id, agent_id)
        tail = request.match_info.get("tail", "")
        url = f"{base}/{tail}" if tail else base
        if request.query_string:
            url += f"?{request.query_string}"
        headers = {
            k: v
            for k, v in request.headers.items()
            if k.lower() not in self._HOP_HEADERS
        }
        body = await request.read() if request.can_read_body else None
        try:
            session = await self._proxy_session()
            async with session.request(
                request.method, url, data=body, headers=headers,
                allow_redirects=False,
            ) as upstream:
                payload = await upstream.read()
                out_headers = {
                    k: v
                    for k, v in upstream.headers.items()
                    if k.lower() not in self._HOP_HEADERS
                }
                return web.Response(
                    status=upstream.status, body=payload,
                    headers=out_headers,
                )
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            raise web.HTTPBadGateway(
                reason=f"agent {agent_id!r} service unreachable: {e}"
            )

    # ------------------------------------------------------------------
    # service gateway: request/response over topics
    # ------------------------------------------------------------------

    async def _http_service(self, request: web.Request) -> web.Response:
        tenant, app_id, gateway, streaming, params, options, credentials = (
            self._context(request)
        )
        if gateway.type != Gateway.SERVICE:
            raise web.HTTPBadRequest(reason="not a service gateway")
        try:
            principal = await self._authenticate(gateway, credentials)
        except AuthenticationException as e:
            raise web.HTTPUnauthorized(reason=str(e))
        service = gateway.service_options
        agent_id = service.get("agent-id")
        if agent_id:
            # agent-proxy mode (parity: GatewayResource.java:235-241):
            # forward the request to the agent's service URI verbatim
            return await self._proxy_to_agent(
                request, tenant, app_id, agent_id
            )
        input_topic = service.get("input-topic")
        output_topic = service.get("output-topic")
        if not input_topic or not output_topic:
            raise web.HTTPBadRequest(
                reason="service gateway needs input-topic/output-topic "
                "(topic mode) or agent-id (proxy mode)"
            )
        import uuid

        correlation = str(uuid.uuid4())
        payload = await self._json_body(request) if request.can_read_body else {}
        runtime = TopicConnectionsRuntimeRegistry.get_runtime(streaming)
        reader = runtime.create_reader(
            {"topic": output_topic}, initial_position="latest"
        )
        await reader.start()
        producer = runtime.create_producer("gateway-service", {"topic": input_topic})
        await producer.start()
        # service round-trips stamp the QoS identity too (the engine's own
        # buckets backstop them); gateway-side shedding stays on the
        # produce/chat paths where a retry hint is actionable
        limiter = self.registry.qos_limiter(tenant, app_id)
        _, qos_priority = self._qos_identity(params, principal)
        inject = {
            **self._mapped_headers(gateway.produce_headers, params, principal),
            **self._qos_headers(limiter, params, principal),
        }
        headers, span = self._traced_headers(
            {
                **(payload.get("headers") or {}),
                **inject,
                "langstream-service-request-id": correlation,
            },
            "gateway.service",
        )
        self._stamp_replica(
            headers, tenant, app_id, params, principal,
            value=payload.get("value"),
        )
        self._stamp_deadline(headers, limiter, params, qos_priority)
        self._journey_produce(headers)
        try:
            # `with span:` so a broker failure mid-write/read still closes
            # the span with its error (end() is idempotent — the explicit
            # ends below keep their timings and error labels)
            with span:
                await producer.write(
                    make_record(
                        value=payload.get("value", payload),
                        key=payload.get("key"),
                        headers=headers,
                    )
                )
                deadline = asyncio.get_event_loop().time() + float(
                    service.get("timeout-seconds", 30)
                )
                while asyncio.get_event_loop().time() < deadline:
                    for record in await reader.read(timeout=0.5):
                        if (
                            record.header("langstream-service-request-id")
                            == correlation
                        ):
                            span.end()
                            return web.json_response(
                                self._record_json(record),
                                headers={TRACE_HEADER: headers[TRACE_HEADER]},
                            )
                span.end(error="timeout")
                raise web.HTTPGatewayTimeout(
                    reason="no response on output topic"
                )
        finally:
            await producer.close()
            await reader.close()
            await runtime.close()
