"""External-agent gRPC protocol (the sidecar lane).

Parity: ``langstream-agent-grpc`` — the reference runs user Python code in a
sidecar interpreter behind a localhost gRPC bidi-stream protocol
(``agent.proto``, ``PythonGrpcServer.java:31``, ``grpc_service.py``). In
this framework Python user code loads in-process by default
(:mod:`langstream_tpu.agents.python_custom`); this package provides the
*out-of-process* lane for code that needs interpreter isolation (conflicting
deps, crash containment) or another language entirely.

Toolchain note: the image ships ``protoc`` and the protobuf runtime but not
``grpcio-tools``, so message classes are generated from ``agent.proto`` by
invoking ``protoc`` on demand (content-hash cached, same pattern as the
native broker build) and the service stubs are hand-written against
``grpc.aio``'s generic handler API in :mod:`proto`.
"""

from langstream_tpu.grpc.proto import load_messages

__all__ = ["load_messages"]
