"""Runtime-side agents that drive an external (sidecar) agent process.

Parity: the Java half of ``langstream-agent-grpc`` —
``AbstractGrpcAgent`` (bidi stream management, out-of-order results by
record-id correlation, ``AbstractGrpcAgent.java:54``,
``GrpcAgentProcessor.java:31``) and ``PythonGrpcServer`` (spawns
``python -m langstream_grpc`` on a free localhost port with PYTHONPATH set
to the app's ``python/`` dirs, ``PythonGrpcServer.java:53-77``), including
restart-on-exit.

Config: ``className`` spawns a sidecar interpreter; ``endpoint`` connects to
an already-running external agent (any language implementing
``agent.proto``).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import subprocess
import sys
import tempfile
from pathlib import Path
from typing import Any

import grpc

from langstream_tpu.api.agent import (
    AgentProcessor,
    AgentSink,
    AgentSource,
    RecordSink,
    SourceRecordAndResult,
)
from langstream_tpu.api.record import Record
from langstream_tpu.grpc.codec import record_from_proto, record_to_proto
from langstream_tpu.grpc.proto import SERVICE_NAME, load_messages, method_table

log = logging.getLogger("langstream_tpu.grpc.client")


class SidecarProcess:
    """Spawns and supervises the external agent interpreter."""

    #: max seconds for the child to report its port (covers interpreter boot
    #: + user-code imports); a wedged boot must fail, not hang the deploy
    START_TIMEOUT = 60.0

    def __init__(self, config: dict[str, Any]):
        self.config = config
        self.process: subprocess.Popen | None = None
        self.port: int | None = None
        self._config_file: Path | None = None

    def start(self) -> int:
        fd, path = tempfile.mkstemp(prefix="ls-sidecar-", suffix=".json")
        self._config_file = Path(path)
        with os.fdopen(fd, "w") as f:
            json.dump(self.config, f)
        env = dict(os.environ)
        python_paths = [str(Path(__file__).resolve().parents[2])]
        app_dir = self.config.get("__application_directory__")
        if app_dir:
            python_paths += [
                str(Path(app_dir) / "python"),
                str(Path(app_dir) / "python" / "lib"),
            ]
        if env.get("PYTHONPATH"):
            python_paths.append(env["PYTHONPATH"])
        env["PYTHONPATH"] = os.pathsep.join(python_paths)
        # NAR-equivalent dependency isolation: an app that pins
        # requirements.txt gets its own venv, and its sidecars run on that
        # interpreter (runtime/isolation.py)
        from langstream_tpu.runtime.isolation import ensure_app_interpreter

        interpreter = ensure_app_interpreter(app_dir)
        self.process = subprocess.Popen(
            [interpreter, "-m", "langstream_tpu.grpc.server", path],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL if os.environ.get(
                "LS_SIDECAR_QUIET") else None,
            env=env,
            text=True,
        )
        # watchdog: kill the child if it never reports its port, so the
        # blocking readline below is guaranteed to return
        import threading

        booted = threading.Event()

        def watchdog() -> None:
            if not booted.wait(self.START_TIMEOUT) and self.process.poll() is None:
                log.error("sidecar boot timed out; killing it")
                self.process.kill()

        threading.Thread(target=watchdog, daemon=True).start()
        for line in self.process.stdout:  # type: ignore[union-attr]
            if line.startswith("PORT="):
                booted.set()
                self.port = int(line.strip().split("=", 1)[1])
                self._start_stdout_drain()
                return self.port
        booted.set()
        raise RuntimeError(
            "sidecar process exited (or timed out) before reporting its "
            f"port (rc={self.process.poll()})"
        )

    def _start_stdout_drain(self) -> None:
        """Keep reading the child's stdout forever — user code that print()s
        would otherwise fill the pipe buffer and deadlock the sidecar."""
        import threading

        def drain(stream):
            try:
                for line in stream:
                    log.debug("sidecar: %s", line.rstrip())
            except (ValueError, OSError):
                pass

        threading.Thread(
            target=drain, args=(self.process.stdout,), daemon=True
        ).start()

    def alive(self) -> bool:
        return self.process is not None and self.process.poll() is None

    def stop(self) -> None:
        if self.process is not None and self.process.poll() is None:
            self.process.terminate()
            try:
                self.process.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.process.kill()
        if self._config_file is not None:
            self._config_file.unlink(missing_ok=True)


class _GrpcAgentBase:
    """Channel + stubs + optional sidecar lifecycle shared by the roles."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.configuration = dict(configuration)
        self.pb2 = load_messages()
        self.sidecar: SidecarProcess | None = None
        self._tp_task: asyncio.Task | None = None
        self.context = None
        # cleared while a restart is in flight: writers wait instead of
        # erroring records into a dead RPC
        self._transport_ready = asyncio.Event()

    async def _connect(self) -> None:
        endpoint = self.configuration.get("endpoint")
        if not endpoint:
            self.sidecar = SidecarProcess(self.configuration)
            loop = asyncio.get_running_loop()
            port = await loop.run_in_executor(None, self.sidecar.start)
            endpoint = f"127.0.0.1:{port}"
        self.channel = grpc.aio.insecure_channel(endpoint)
        self.stubs = {}
        for name, spec in method_table(self.pb2).items():
            path = f"/{SERVICE_NAME}/{name}"
            if spec["kind"] == "unary_unary":
                self.stubs[name] = self.channel.unary_unary(
                    path,
                    request_serializer=spec["request"].SerializeToString,
                    response_deserializer=spec["response"].FromString,
                )
            else:
                self.stubs[name] = self.channel.stream_stream(
                    path,
                    request_serializer=spec["request"].SerializeToString,
                    response_deserializer=spec["response"].FromString,
                )

    async def setup(self, context) -> None:
        self.context = context

    async def start(self) -> None:
        await self._connect()
        # records the sidecar asks us to publish on arbitrary topics
        self._tp_task = asyncio.ensure_future(self._pump_topic_producers())
        self._transport_ready.set()

    async def _await_transport(self, timeout: float = 60.0) -> None:
        await asyncio.wait_for(self._transport_ready.wait(), timeout)

    async def _pump_topic_producers(self) -> None:
        call = self.stubs["topic_producer_records"]()
        producers: dict[str, Any] = {}
        try:
            async for msg in call:
                ack = self.pb2.TopicProducerAck(record_id=msg.record_id)
                try:
                    # decode inside the guarded block: a malformed record
                    # must become a failed ack, not a dead pump (a dead pump
                    # leaves the sidecar's write awaiting forever)
                    record = record_from_proto(msg.record)
                    if self.context is None:
                        raise RuntimeError("agent context not set")
                    if msg.topic not in producers:
                        producers[msg.topic] = self.context.get_topic_producer(
                            msg.topic
                        )
                    await producers[msg.topic].write(record)
                except Exception as e:
                    log.warning(
                        "topic-producer publish to %s failed: %s", msg.topic, e
                    )
                    ack.error = str(e)
                await call.write(ack)
        except (asyncio.CancelledError, grpc.aio.AioRpcError):
            pass
        finally:
            # end the stream on any exit so the server fails still-pending
            # writes instead of leaving them suspended on a silent channel
            try:
                call.cancel()
            except Exception as e:
                log.debug("topic-producer stream cancel failed: %s", e)

    async def _restart_transport(self) -> bool:
        """Respawn a dead sidecar and reconnect (parity: the reference's
        restart support in ``PythonGrpcServer``). Bounded attempts; on
        exhaustion the caller escalates via ``context.critical_failure`` so
        the replica restarts (kubelet / local runner)."""
        if self.sidecar is None:  # external endpoint: nothing to respawn
            return False
        self._restarts = getattr(self, "_restarts", 0) + 1
        if self._restarts > 3:
            return False
        log.warning("sidecar died; restart attempt %d/3", self._restarts)
        self._transport_ready.clear()
        loop = asyncio.get_running_loop()
        if self._tp_task is not None:
            self._tp_task.cancel()
        try:
            await self.channel.close()
        except Exception as e:  # noqa: BLE001
            log.debug("closing dead channel failed: %s", e)
        await loop.run_in_executor(None, self.sidecar.stop)
        try:
            await self._connect()
        except Exception as e:  # noqa: BLE001
            log.error("sidecar restart failed: %s", e)
            return False
        self._tp_task = asyncio.ensure_future(self._pump_topic_producers())
        return True

    def _escalate(self, error: Exception) -> None:
        """No transport left: abort the replica (pod restart recovers)."""
        if self.context is not None:
            self.context.critical_failure(error)
        else:
            log.error("external agent transport lost: %s", error)

    async def fetch_agent_info(self) -> dict[str, Any]:
        """Query the remote agent's info blob (async; the sync
        ``agent_info()`` inherited from AgentCode stays cheap)."""
        try:
            response = await self.stubs["agent_info"](self.pb2.InfoRequest())
            info = json.loads(response.info_json or "{}")
            self._last_info = info
            return info
        except Exception as e:  # noqa: BLE001
            return {"error": str(e)}

    def agent_info(self) -> dict[str, Any]:
        info = dict(getattr(self, "_last_info", {}))
        info["execution"] = "sidecar" if self.sidecar else "external-endpoint"
        return info

    async def close(self) -> None:
        if self._tp_task is not None:
            self._tp_task.cancel()
        if getattr(self, "channel", None) is not None:
            await self.channel.close()
        if self.sidecar is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self.sidecar.stop
            )


class GrpcAgentProcessor(_GrpcAgentBase, AgentProcessor):
    """``grpc-python-processor`` — results may complete out of order; the
    record_id correlation maps them back to source records."""

    async def init(self, configuration: dict[str, Any]) -> None:
        await super().init(configuration)
        self._ids = iter(range(1, 1 << 62))
        self._inflight: dict[int, tuple[Record, RecordSink]] = {}
        self._call = None
        self._reader: asyncio.Task | None = None
        # strong refs: the loop only weak-refs tasks, and a GC'd _send task
        # would strand its records in _inflight forever
        self._send_tasks: set[asyncio.Task] = set()

    async def start(self) -> None:
        await super().start()
        self._call = self.stubs["process"]()
        # grpc.aio allows one in-flight write per stream; the runner emits
        # batches concurrently, so writes serialize behind a lock
        self._write_lock = asyncio.Lock()
        self._reader = asyncio.ensure_future(self._read_results())

    async def _read_results(self) -> None:
        try:
            async for response in self._call:
                for result in response.results:
                    entry = self._inflight.pop(result.record_id, None)
                    if entry is None:
                        log.warning(
                            "orphan result for record id %d", result.record_id
                        )
                        continue
                    source, sink = entry
                    if result.error:
                        sink.emit_error(source, RuntimeError(result.error))
                    else:
                        sink.emit(
                            SourceRecordAndResult(
                                source,
                                [record_from_proto(m) for m in result.records],
                                None,
                            )
                        )
        except asyncio.CancelledError:
            return
        except grpc.aio.AioRpcError as e:
            # a dead sidecar fails every in-flight record; the runtime's
            # error policy (retry/dead-letter/fail) takes it from there
            inflight, self._inflight = self._inflight, {}
            for source, sink in inflight.values():
                sink.emit_error(source, RuntimeError(f"sidecar stream lost: {e}"))
            if await self._restart_transport():
                self._call = self.stubs["process"]()
                self._reader = asyncio.ensure_future(self._read_results())
                self._transport_ready.set()
            else:
                self._escalate(RuntimeError(f"sidecar process lost: {e}"))

    def process(self, records: list[Record], sink: RecordSink) -> None:
        task = asyncio.ensure_future(self._send(records, sink))
        self._send_tasks.add(task)
        task.add_done_callback(self._send_tasks.discard)

    async def _send(self, records: list[Record], sink: RecordSink) -> None:
        try:
            await self._await_transport()
        except asyncio.TimeoutError as e:
            for record in records:
                sink.emit_error(record, e)
            return
        request = self.pb2.ProcessRequest()
        rids = []
        for record in records:
            rid = next(self._ids)
            rids.append(rid)
            self._inflight[rid] = (record, sink)
            request.records.append(record_to_proto(self.pb2, record, rid))
        try:
            async with self._write_lock:
                await self._call.write(request)
        except Exception as e:  # stream write failed → all records error
            for rid, record in zip(rids, records):
                # drop from in-flight FIRST: the reader's stream-lost cleanup
                # must not error the same records a second time
                self._inflight.pop(rid, None)
                sink.emit_error(record, e)

    async def close(self) -> None:
        if self._reader is not None:
            self._reader.cancel()
        await super().close()


class GrpcAgentSource(_GrpcAgentBase, AgentSource):
    """``grpc-python-source`` — the sidecar's reads stream in; commits and
    permanent failures stream back.

    Correlation uses an instance-identity map (the runner commits the very
    record objects it read), not a header: transport ids must never leak
    into downstream topics."""

    async def start(self) -> None:
        await super().start()
        self._call = self.stubs["read"]()
        self._queue: asyncio.Queue = asyncio.Queue()
        self._write_lock = asyncio.Lock()
        # id(record) → (record, sidecar id); holding the record ref keeps
        # the object alive so CPython can't reuse its id while in flight
        self._ids_by_obj: dict[int, tuple[Record, int]] = {}
        self._reader = asyncio.ensure_future(self._read_batches())

    async def _read_batches(self) -> None:
        try:
            async for response in self._call:
                batch = []
                for msg in response.records:
                    record = record_from_proto(msg)
                    self._ids_by_obj[id(record)] = (record, msg.record_id)
                    batch.append(record)
                await self._queue.put(batch)
        except asyncio.CancelledError:
            return
        except grpc.aio.AioRpcError as e:
            # uncommitted reads die with the sidecar; the restarted user
            # source resumes from its own checkpoint (at-least-once)
            self._ids_by_obj.clear()
            if await self._restart_transport():
                self._call = self.stubs["read"]()
                self._reader = asyncio.ensure_future(self._read_batches())
                self._transport_ready.set()
            else:
                self._escalate(RuntimeError(f"sidecar source lost: {e}"))

    async def read(self) -> list[Record]:
        try:
            return await asyncio.wait_for(self._queue.get(), timeout=0.5)
        except asyncio.TimeoutError:
            return []

    def _pop_sidecar_id(self, record: Record) -> int | None:
        entry = self._ids_by_obj.pop(id(record), None)
        return entry[1] if entry else None

    async def commit(self, records: list[Record]) -> None:
        ids = [
            rid
            for rid in (self._pop_sidecar_id(r) for r in records)
            if rid is not None
        ]
        if ids:
            await self._await_transport()
            async with self._write_lock:
                await self._call.write(
                    self.pb2.SourceRequest(committed_ids=ids)
                )

    async def permanent_failure(self, record: Record, error: Exception) -> None:
        rid = self._pop_sidecar_id(record)
        if rid is not None:
            await self._await_transport()
            async with self._write_lock:
                await self._call.write(
                    self.pb2.SourceRequest(
                        failed_id=rid, failure_error=str(error)
                    )
                )
        raise error

    async def close(self) -> None:
        if getattr(self, "_reader", None) is not None:
            self._reader.cancel()
        await super().close()


class GrpcAgentSink(_GrpcAgentBase, AgentSink):
    """``grpc-python-sink`` — writes await the sidecar's per-record ack."""

    async def init(self, configuration: dict[str, Any]) -> None:
        await super().init(configuration)
        self._ids = iter(range(1, 1 << 62))
        self._acks: dict[int, asyncio.Future] = {}

    async def start(self) -> None:
        await super().start()
        self._call = self.stubs["write"]()
        self._write_lock = asyncio.Lock()
        self._reader = asyncio.ensure_future(self._read_acks())

    async def _read_acks(self) -> None:
        try:
            async for response in self._call:
                future = self._acks.pop(response.record_id, None)
                if future is None or future.done():
                    continue
                if response.error:
                    future.set_exception(RuntimeError(response.error))
                else:
                    future.set_result(None)
        except asyncio.CancelledError:
            return
        except grpc.aio.AioRpcError as e:
            acks, self._acks = self._acks, {}
            for future in acks.values():
                if not future.done():
                    future.set_exception(RuntimeError(f"sidecar lost: {e}"))
            if await self._restart_transport():
                self._call = self.stubs["write"]()
                self._reader = asyncio.ensure_future(self._read_acks())
                self._transport_ready.set()
            else:
                self._escalate(RuntimeError(f"sidecar sink lost: {e}"))

    async def write(self, record: Record) -> None:
        await self._await_transport()
        rid = next(self._ids)
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        self._acks[rid] = future
        request = self.pb2.SinkRequest()
        request.record.CopyFrom(record_to_proto(self.pb2, record, rid))
        try:
            async with self._write_lock:
                await self._call.write(request)
        except Exception:
            self._acks.pop(rid, None)  # nobody will await it
            future.cancel()
            raise
        await future

    async def close(self) -> None:
        if getattr(self, "_reader", None) is not None:
            self._reader.cancel()
        await super().close()
