"""Record ↔ wire-message conversion for the external-agent protocol."""

from __future__ import annotations

import json
from typing import Any

from langstream_tpu.api.record import Record, make_record
from langstream_tpu.api.topics import OFFSET_HEADER


def datum_to_proto(pb2, value: Any):
    d = pb2.Datum()
    if value is None:
        d.null_value = True
    elif isinstance(value, bytes):
        d.bytes_value = value
    elif isinstance(value, str):
        d.string_value = value
    else:
        d.json_value = json.dumps(value)
    return d


def datum_from_proto(d) -> Any:
    kind = d.WhichOneof("kind")
    if kind is None or kind == "null_value":
        return None
    if kind == "bytes_value":
        return d.bytes_value
    if kind == "string_value":
        return d.string_value
    return json.loads(d.json_value)


def record_to_proto(pb2, record: Record, record_id: int):
    msg = pb2.WireRecord(
        record_id=record_id,
        origin=record.origin or "",
        timestamp=record.timestamp or 0,
    )
    msg.key.CopyFrom(datum_to_proto(pb2, record.key))
    msg.value.CopyFrom(datum_to_proto(pb2, record.value))
    for name, value in record.headers:
        if name == OFFSET_HEADER:
            continue  # transport-local, never crosses the process boundary
        header = msg.headers.add()
        header.name = name
        header.value.CopyFrom(datum_to_proto(pb2, value))
    return msg


def record_from_proto(msg) -> Record:
    return make_record(
        value=datum_from_proto(msg.value),
        key=datum_from_proto(msg.key),
        headers=[(h.name, datum_from_proto(h.value)) for h in msg.headers],
        origin=msg.origin or None,
        timestamp=msg.timestamp or None,
    )
