"""On-demand protobuf codegen + hand-written gRPC method table.

``protoc --python_out`` runs once per proto-file content hash (no
``grpcio-tools`` in the image, so the service layer is defined here as a
method table both the aio server and the client build from). Images with
no ``protoc`` binary either fall back to :func:`_fallback_messages` — the
same messages built as a ``FileDescriptorProto`` against the installed
protobuf runtime, wire-compatible with protoc output because field numbers
and types are identical.
"""

from __future__ import annotations

import hashlib
import importlib.util
import shutil
import subprocess
import sys
import tempfile
from pathlib import Path

_HERE = Path(__file__).parent
PROTO_FILE = _HERE / "agent.proto"
_GEN_DIR = _HERE / "_gen"

SERVICE_NAME = "langstream_tpu.ExternalAgent"


class ProtoBuildError(RuntimeError):
    pass


def load_messages():
    """Generate (if needed) and import the ``agent_pb2`` message module.

    Resolution order: cached protoc output for this proto hash → a fresh
    ``protoc`` run → :func:`_fallback_messages` when no protoc binary
    exists in the image."""
    digest = hashlib.sha256(PROTO_FILE.read_bytes()).hexdigest()[:16]
    gen_dir = _GEN_DIR / digest
    target = gen_dir / "agent_pb2.py"
    if not target.exists():
        if shutil.which("protoc") is None:
            return _fallback_messages()
        gen_dir.mkdir(parents=True, exist_ok=True)
        with tempfile.TemporaryDirectory() as tmp:
            proc = subprocess.run(
                [
                    "protoc",
                    f"--proto_path={PROTO_FILE.parent}",
                    f"--python_out={tmp}",
                    PROTO_FILE.name,
                ],
                capture_output=True,
                text=True,
            )
            if proc.returncode != 0:
                raise ProtoBuildError(f"protoc failed:\n{proc.stderr}")
            generated = Path(tmp) / "agent_pb2.py"
            target.write_bytes(generated.read_bytes())
    spec = importlib.util.spec_from_file_location(
        f"langstream_tpu_agent_pb2_{digest}", target
    )
    module = importlib.util.module_from_spec(spec)
    # protobuf-generated modules self-register by module name
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


_FALLBACK_CACHE = None


def _fallback_messages():
    """``agent.proto`` compiled in-process, no protoc: the schema rebuilt
    as a ``FileDescriptorProto`` against the installed protobuf runtime.

    Wire-compatible with protoc output — field numbers, types, and labels
    below mirror ``agent.proto`` exactly, so a sidecar running the
    protoc-generated module interoperates with a runtime running this one
    (and vice versa). Kept in sync by ``tests/test_grpc_agents.py``, which
    exercises every message over a real channel.
    """
    global _FALLBACK_CACHE
    if _FALLBACK_CACHE is not None:
        return _FALLBACK_CACHE
    import types

    from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

    T = descriptor_pb2.FieldDescriptorProto
    fd = descriptor_pb2.FileDescriptorProto(
        name="langstream_tpu/agent_fallback.proto",
        package="langstream_tpu",
        syntax="proto3",
    )

    # (name, number, type, label, message type name, oneof index)
    SCHEMA: dict[str, list[tuple]] = {
        "Datum": [
            ("null_value", 1, T.TYPE_BOOL, None, None, 0),
            ("bytes_value", 2, T.TYPE_BYTES, None, None, 0),
            ("string_value", 3, T.TYPE_STRING, None, None, 0),
            ("json_value", 4, T.TYPE_STRING, None, None, 0),
        ],
        "Header": [
            ("name", 1, T.TYPE_STRING, None, None, None),
            ("value", 2, T.TYPE_MESSAGE, None, "Datum", None),
        ],
        "WireRecord": [
            ("record_id", 1, T.TYPE_INT64, None, None, None),
            ("key", 2, T.TYPE_MESSAGE, None, "Datum", None),
            ("value", 3, T.TYPE_MESSAGE, None, "Datum", None),
            ("headers", 4, T.TYPE_MESSAGE, T.LABEL_REPEATED, "Header", None),
            ("origin", 5, T.TYPE_STRING, None, None, None),
            ("timestamp", 6, T.TYPE_INT64, None, None, None),
        ],
        "InfoRequest": [],
        "InfoResponse": [("info_json", 1, T.TYPE_STRING, None, None, None)],
        "SourceRequest": [
            ("committed_ids", 1, T.TYPE_INT64, T.LABEL_REPEATED, None, None),
            ("failed_id", 2, T.TYPE_INT64, None, None, None),
            ("failure_error", 3, T.TYPE_STRING, None, None, None),
        ],
        "SourceResponse": [
            ("records", 1, T.TYPE_MESSAGE, T.LABEL_REPEATED, "WireRecord", None),
        ],
        "ProcessRequest": [
            ("records", 1, T.TYPE_MESSAGE, T.LABEL_REPEATED, "WireRecord", None),
        ],
        "ProcessResult": [
            ("record_id", 1, T.TYPE_INT64, None, None, None),
            ("records", 2, T.TYPE_MESSAGE, T.LABEL_REPEATED, "WireRecord", None),
            ("error", 3, T.TYPE_STRING, None, None, None),
        ],
        "ProcessResponse": [
            ("results", 1, T.TYPE_MESSAGE, T.LABEL_REPEATED, "ProcessResult", None),
        ],
        "SinkRequest": [
            ("record", 1, T.TYPE_MESSAGE, None, "WireRecord", None),
        ],
        "SinkResponse": [
            ("record_id", 1, T.TYPE_INT64, None, None, None),
            ("error", 2, T.TYPE_STRING, None, None, None),
        ],
        "TopicProducerRecord": [
            ("record_id", 1, T.TYPE_INT64, None, None, None),
            ("topic", 2, T.TYPE_STRING, None, None, None),
            ("record", 3, T.TYPE_MESSAGE, None, "WireRecord", None),
        ],
        "TopicProducerAck": [
            ("record_id", 1, T.TYPE_INT64, None, None, None),
            ("error", 2, T.TYPE_STRING, None, None, None),
        ],
    }
    for msg_name, fields in SCHEMA.items():
        m = fd.message_type.add(name=msg_name)
        if msg_name == "Datum":
            m.oneof_decl.add(name="kind")
        for name, number, ftype, label, type_name, oneof in fields:
            f = m.field.add(
                name=name, number=number, type=ftype,
                label=label if label is not None else T.LABEL_OPTIONAL,
            )
            if type_name is not None:
                f.type_name = f".langstream_tpu.{type_name}"
            if oneof is not None:
                f.oneof_index = oneof

    # private pool: never collides with a protoc-generated module loaded
    # into the default pool by another component in this process
    pool = descriptor_pool.DescriptorPool()
    classes = message_factory.GetMessages([fd], pool=pool)
    _FALLBACK_CACHE = types.SimpleNamespace(
        **{full.rsplit(".", 1)[1]: cls for full, cls in classes.items()}
    )
    return _FALLBACK_CACHE


def method_table(pb2) -> dict[str, dict]:
    """Every RPC of the ExternalAgent service: name → kind + message types.
    The single source both sides build handlers/stubs from."""
    return {
        "agent_info": {
            "kind": "unary_unary",
            "request": pb2.InfoRequest,
            "response": pb2.InfoResponse,
        },
        "read": {
            "kind": "stream_stream",
            "request": pb2.SourceRequest,
            "response": pb2.SourceResponse,
        },
        "process": {
            "kind": "stream_stream",
            "request": pb2.ProcessRequest,
            "response": pb2.ProcessResponse,
        },
        "write": {
            "kind": "stream_stream",
            "request": pb2.SinkRequest,
            "response": pb2.SinkResponse,
        },
        "topic_producer_records": {
            "kind": "stream_stream",
            "request": pb2.TopicProducerAck,
            "response": pb2.TopicProducerRecord,
        },
    }
