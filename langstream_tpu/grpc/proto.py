"""On-demand protobuf codegen + hand-written gRPC method table.

``protoc --python_out`` runs once per proto-file content hash (no
``grpcio-tools`` in the image, so the service layer is defined here as a
method table both the aio server and the client build from).
"""

from __future__ import annotations

import hashlib
import importlib.util
import subprocess
import sys
import tempfile
from pathlib import Path

_HERE = Path(__file__).parent
PROTO_FILE = _HERE / "agent.proto"
_GEN_DIR = _HERE / "_gen"

SERVICE_NAME = "langstream_tpu.ExternalAgent"


class ProtoBuildError(RuntimeError):
    pass


def load_messages():
    """Generate (if needed) and import the ``agent_pb2`` message module."""
    digest = hashlib.sha256(PROTO_FILE.read_bytes()).hexdigest()[:16]
    gen_dir = _GEN_DIR / digest
    target = gen_dir / "agent_pb2.py"
    if not target.exists():
        gen_dir.mkdir(parents=True, exist_ok=True)
        with tempfile.TemporaryDirectory() as tmp:
            proc = subprocess.run(
                [
                    "protoc",
                    f"--proto_path={PROTO_FILE.parent}",
                    f"--python_out={tmp}",
                    PROTO_FILE.name,
                ],
                capture_output=True,
                text=True,
            )
            if proc.returncode != 0:
                raise ProtoBuildError(f"protoc failed:\n{proc.stderr}")
            generated = Path(tmp) / "agent_pb2.py"
            target.write_bytes(generated.read_bytes())
    spec = importlib.util.spec_from_file_location(
        f"langstream_tpu_agent_pb2_{digest}", target
    )
    module = importlib.util.module_from_spec(spec)
    # protobuf-generated modules self-register by module name
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


def method_table(pb2) -> dict[str, dict]:
    """Every RPC of the ExternalAgent service: name → kind + message types.
    The single source both sides build handlers/stubs from."""
    return {
        "agent_info": {
            "kind": "unary_unary",
            "request": pb2.InfoRequest,
            "response": pb2.InfoResponse,
        },
        "read": {
            "kind": "stream_stream",
            "request": pb2.SourceRequest,
            "response": pb2.SourceResponse,
        },
        "process": {
            "kind": "stream_stream",
            "request": pb2.ProcessRequest,
            "response": pb2.ProcessResponse,
        },
        "write": {
            "kind": "stream_stream",
            "request": pb2.SinkRequest,
            "response": pb2.SinkResponse,
        },
        "topic_producer_records": {
            "kind": "stream_stream",
            "request": pb2.TopicProducerAck,
            "response": pb2.TopicProducerRecord,
        },
    }
