"""The external-agent server: hosts user agent code behind gRPC.

Parity: the reference's Python sidecar ``grpc_service.py`` (asyncio
``AgentService(AgentServiceServicer)`` implementing bidi ``read`` /
``process`` / ``write`` / ``get_topic_producer_records``; ``AgentServer``
binds a localhost port and loads the user class from ``className`` config,
``grpc_service.py:75-229,415``).

Run: ``python -m langstream_tpu.grpc.server <config.json>`` — prints
``PORT=<n>`` on stdout once bound (the runtime's process manager reads it).

The user-code contract is the same duck-typed one the in-process lane
accepts (``init``/``read``/``process``/``write``/``commit``/``agent_info``,
sync or async — see :mod:`langstream_tpu.agents.python_custom`), so moving
an agent between in-process and sidecar execution is a config change, not a
code change.
"""

from __future__ import annotations

import asyncio
import json
import logging
import signal
import sys
from typing import Any

import grpc

from langstream_tpu.api.record import Record
from langstream_tpu.grpc.codec import record_from_proto, record_to_proto
from langstream_tpu.grpc.proto import SERVICE_NAME, load_messages, method_table

log = logging.getLogger("langstream_tpu.grpc.server")


async def _maybe_await(result):
    if hasattr(result, "__await__"):
        return await result
    if asyncio.isfuture(result):
        return await result
    return result


class _TopicProducerHandle:
    """Handed to user code as context.get_topic_producer(topic): queues
    records for the runtime to publish (the topic_producer_records stream)."""

    def __init__(self, service: "ExternalAgentService", topic: str):
        self.service = service
        self.topic = topic

    async def write(self, record: Any) -> None:
        await self.service.queue_topic_producer_record(self.topic, record)


class _SidecarContext:
    def __init__(self, service: "ExternalAgentService", config: dict[str, Any]):
        self.service = service
        self.config = config

    def get_topic_producer(self, topic: str) -> _TopicProducerHandle:
        return _TopicProducerHandle(self.service, topic)

    def get_persistent_state_directory(self) -> str | None:
        return self.config.get("__persistent_state_directory__")


class ExternalAgentService:
    """The servicer: one user agent instance behind the five RPCs."""

    def __init__(self, config: dict[str, Any]):
        self.pb2 = load_messages()
        self.config = config
        self.delegate: Any = None
        self._read_ids = iter(range(1, 1 << 62))
        self._inflight_source: dict[int, Record] = {}
        self._producer_queue: asyncio.Queue = asyncio.Queue()
        self._producer_id = iter(range(1, 1 << 62))
        # writes awaiting their runtime ack, keyed by record_id — the
        # at-least-once half of the topic-producer lane (parity: the
        # reference returns TopicProducerWriteResult per write,
        # ``agent.proto:73-76`` there)
        self._producer_pending: dict[int, asyncio.Future] = {}

    async def start(self) -> None:
        from langstream_tpu.agents.python_custom import _load_user_class

        cls = _load_user_class(self.config)
        self.delegate = cls()
        if hasattr(self.delegate, "init"):
            await _maybe_await(self.delegate.init(self.config))
        if hasattr(self.delegate, "set_context"):
            await _maybe_await(
                self.delegate.set_context(_SidecarContext(self, self.config))
            )

    async def close(self) -> None:
        if self.delegate is not None and hasattr(self.delegate, "close"):
            await _maybe_await(self.delegate.close())

    async def queue_topic_producer_record(self, topic: str, record: Any) -> None:
        """Queue a record for the runtime to publish and wait for its ack —
        user code's ``await producer.write(record)`` returns only once the
        runtime confirmed the write (raises on a failed one). Blocks until a
        runtime is connected, exactly like a broker producer awaiting its
        broker."""
        from langstream_tpu.agents.python_custom import _coerce_result
        from langstream_tpu.api.record import make_record

        coerced = _coerce_result(record, make_record())
        rid = next(self._producer_id)
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        self._producer_pending[rid] = future
        await self._producer_queue.put((rid, topic, coerced))
        try:
            await future
        finally:
            self._producer_pending.pop(rid, None)

    # ---- RPC handlers ----------------------------------------------------

    async def agent_info(self, request, context):
        info: dict[str, Any] = {"className": self.config.get("className", "")}
        if hasattr(self.delegate, "agent_info"):
            info.update(await _maybe_await(self.delegate.agent_info()) or {})
        return self.pb2.InfoResponse(info_json=json.dumps(info))

    async def read(self, request_iterator, context):
        """Bidi: we push record batches; requests carry commits/failures."""

        async def consume_requests():
            async for request in request_iterator:
                records = [
                    self._inflight_source.pop(rid)
                    for rid in request.committed_ids
                    if rid in self._inflight_source
                ]
                if records and hasattr(self.delegate, "commit"):
                    await _maybe_await(self.delegate.commit(records))
                if request.failed_id:
                    failed = self._inflight_source.pop(request.failed_id, None)
                    if hasattr(self.delegate, "permanent_failure"):
                        await _maybe_await(
                            self.delegate.permanent_failure(
                                failed, RuntimeError(request.failure_error)
                            )
                        )

        consumer = asyncio.ensure_future(consume_requests())
        try:
            while not context.cancelled():
                batch = await _maybe_await(self.delegate.read())
                if not batch:
                    await asyncio.sleep(0.05)
                    continue
                from langstream_tpu.agents.python_custom import _coerce_result
                from langstream_tpu.api.record import make_record

                response = self.pb2.SourceResponse()
                for item in batch:
                    record = _coerce_result(item, make_record())
                    rid = next(self._read_ids)
                    self._inflight_source[rid] = record
                    response.records.append(
                        record_to_proto(self.pb2, record, rid)
                    )
                yield response
        finally:
            consumer.cancel()

    async def process(self, request_iterator, context):
        """Bidi with out-of-order completion: each record is processed in
        its own task; results stream back as they finish, correlated by
        record_id (parity: ``GrpcAgentProcessor`` correlation)."""
        results: asyncio.Queue = asyncio.Queue()
        pending: set[asyncio.Task] = set()

        async def run_one(msg):
            record = record_from_proto(msg)
            result = self.pb2.ProcessResult(record_id=msg.record_id)
            try:
                out = await _maybe_await(self.delegate.process(record))
                if out is None:
                    out = []
                if not isinstance(out, list):
                    out = [out]
                from langstream_tpu.agents.python_custom import _coerce_result

                for item in out:
                    coerced = _coerce_result(item, record)
                    result.records.append(
                        record_to_proto(self.pb2, coerced, msg.record_id)
                    )
            except Exception as e:  # error travels back, policy is runtime-side
                result.error = f"{type(e).__name__}: {e}"
            await results.put(result)

        async def consume_requests():
            async for request in request_iterator:
                for msg in request.records:
                    task = asyncio.ensure_future(run_one(msg))
                    pending.add(task)
                    task.add_done_callback(pending.discard)
            await asyncio.gather(*list(pending), return_exceptions=True)
            await results.put(None)  # sentinel: input closed and drained

        consumer = asyncio.ensure_future(consume_requests())
        try:
            while True:
                result = await results.get()
                if result is None:
                    break
                response = self.pb2.ProcessResponse()
                response.results.append(result)
                yield response
        finally:
            consumer.cancel()

    async def write(self, request_iterator, context):
        async for request in request_iterator:
            msg = request.record
            response = self.pb2.SinkResponse(record_id=msg.record_id)
            try:
                await _maybe_await(self.delegate.write(record_from_proto(msg)))
            except Exception as e:
                response.error = f"{type(e).__name__}: {e}"
            yield response

    async def topic_producer_records(self, request_iterator, context):
        async def consume_acks():
            async for ack in request_iterator:
                future = self._producer_pending.get(ack.record_id)
                if future is None or future.done():
                    continue
                if ack.error:
                    future.set_exception(
                        RuntimeError(f"topic producer write failed: {ack.error}")
                    )
                else:
                    future.set_result(None)

        consumer = asyncio.ensure_future(consume_acks())
        try:
            while not context.cancelled():
                rid, topic, record = await self._producer_queue.get()
                msg = self.pb2.TopicProducerRecord(record_id=rid, topic=topic)
                msg.record.CopyFrom(record_to_proto(self.pb2, record, rid))
                yield msg
        finally:
            consumer.cancel()
            # the runtime went away: in-flight writes must not hang — fail
            # them so user code can retry once the stream is re-established
            for future in list(self._producer_pending.values()):
                if not future.done():
                    future.set_exception(
                        RuntimeError(
                            "runtime disconnected before acking the write"
                        )
                    )


class AgentServer:
    """Binds the servicer on localhost (parity: ``AgentServer``,
    ``grpc_service.py:415``)."""

    def __init__(self, config: dict[str, Any], port: int = 0):
        self.service = ExternalAgentService(config)
        self.requested_port = port
        self.port: int | None = None
        self._server: grpc.aio.Server | None = None

    async def start(self) -> int:
        await self.service.start()
        pb2 = self.service.pb2
        handlers = {}
        for name, spec in method_table(pb2).items():
            handler_fn = getattr(self.service, name)
            if spec["kind"] == "unary_unary":
                handlers[name] = grpc.unary_unary_rpc_method_handler(
                    handler_fn,
                    request_deserializer=spec["request"].FromString,
                    response_serializer=spec["response"].SerializeToString,
                )
            else:
                handlers[name] = grpc.stream_stream_rpc_method_handler(
                    handler_fn,
                    request_deserializer=spec["request"].FromString,
                    response_serializer=spec["response"].SerializeToString,
                )
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
        )
        self.port = self._server.add_insecure_port(
            f"127.0.0.1:{self.requested_port}"
        )
        await self._server.start()
        return self.port

    async def stop(self, grace: float = 5.0) -> None:
        if self._server is not None:
            await self._server.stop(grace)
        await self.service.close()


async def _main(config_path: str) -> None:
    config = json.loads(
        sys.stdin.read() if config_path == "-" else open(config_path).read()
    )
    server = AgentServer(config)
    port = await server.start()
    print(f"PORT={port}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await server.stop()


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    asyncio.run(_main(sys.argv[1] if len(sys.argv) > 1 else "-"))
