"""Version-compatibility shims for the JAX surface this tree uses.

The codebase targets the current JAX API (``jax.shard_map`` with
``check_vma``, ``pltpu.CompilerParams``); the image may pin an older 0.4.x
release where ``shard_map`` still lives in ``jax.experimental.shard_map``
(with the ``check_rep`` spelling) and the Pallas TPU compiler-params
dataclass is named ``TPUCompilerParams``. Every shard_map /
compiler-params consumer imports from here so either version works — one
resolution point instead of a try/except per call site.
"""

from __future__ import annotations

import inspect

import jax

if hasattr(jax, "shard_map"):
    _shard_map_impl = jax.shard_map
else:  # jax < 0.6: the experimental home
    from jax.experimental.shard_map import shard_map as _shard_map_impl

_SHARD_MAP_PARAMS = frozenset(
    inspect.signature(_shard_map_impl).parameters
)

# Partial-manual shard_map (some axes manual, the rest automatic) only
# works on jax versions with the ``axis_names`` parameter; the older
# ``auto=`` spelling miscompiles on CPU (XLA "PartitionId is not supported
# for SPMD partitioning"). Callers that ONLY need partial-auto as an
# optimisation (e.g. in-stage sharding constraints inside a pipeline
# stage) check this and degrade to replicated compute on old jax.
SHARD_MAP_PARTIAL_AUTO = "axis_names" in _SHARD_MAP_PARAMS


def shard_map(f, /, **kwargs):
    """``jax.shard_map`` under either replication-check spelling.

    Callers write the current ``check_vma=...``; on a jax whose shard_map
    only knows ``check_rep`` (or vice versa) the kwarg is renamed to the
    one the installed version accepts.
    """
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _SHARD_MAP_PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    if "axis_names" in kwargs and "axis_names" not in _SHARD_MAP_PARAMS:
        # no partial-manual support: run fully manual. Axes the specs never
        # mention are replicated, so the result is identical — non-manual
        # axes just lose automatic sharding inside the body (see
        # SHARD_MAP_PARTIAL_AUTO for how bodies degrade their constraints).
        kwargs.pop("axis_names")
        kwargs.pop("check_vma", None); kwargs["check_rep"] = False
    return _shard_map_impl(f, **kwargs)


def pallas_compiler_params():
    """The Pallas TPU compiler-params class under its current name.

    Resolved lazily (function, not module attribute) so importing this
    module never pulls in Pallas — kernel modules already import it, but
    ``parallel/`` shard_map users must stay Pallas-free on backends where
    Pallas is unavailable.
    """
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:  # the jax < 0.5 name
        cls = pltpu.TPUCompilerParams
    return cls
