"""L3b/L5: the Kubernetes control & data plane.

Parity map (reference → here):

- CRD POJOs (``langstream-k8s-deployer-api/.../crds/*``)        → :mod:`crds`
- ``AgentResourcesFactory`` / ``AppResourcesFactory``
  (``langstream-k8s-deployer-core``)                             → :mod:`resources`
- ``KubernetesClusterRuntime`` (``langstream-k8s-runtime``)      → :mod:`cluster_runtime`
- operator reconcilers (``langstream-k8s-deployer-operator``)    → :mod:`operator`
- app/metadata stores (``langstream-k8s-storage``)               → :mod:`stores`
- ``SpecDiffer`` / limits checker                                → :mod:`diff`, :mod:`limits`
- fabric8 client + ``KubeTestServer`` (``langstream-k8s-common``)→ :mod:`client`

TPU-first departures: agent pods schedule onto GKE TPU node pools
(``google.com/tpu`` resources, accelerator/topology node selectors derived
from the agent's ``device-mesh``), and a multi-host ICI slice is one
*logical* replica — the factory emits one StatefulSet per logical replica
whose pods form the JAX distributed process group (coordinator = ordinal 0
via the headless service), instead of the reference's replicas=parallelism
single-host mapping.
"""

from langstream_tpu.k8s.client import InMemoryKubeApi, KubeApi
from langstream_tpu.k8s.cluster_runtime import KubernetesClusterRuntime
from langstream_tpu.k8s.crds import AgentCustomResource, ApplicationCustomResource

__all__ = [
    "AgentCustomResource",
    "ApplicationCustomResource",
    "InMemoryKubeApi",
    "KubeApi",
    "KubernetesClusterRuntime",
]
