"""An embedded conformance-grade Kubernetes API server (HTTP, in-process).

Two roles:
1. **Test double** (born as ``tests/fake_kube.py``): the operator/
   deployer/stores are proven against real API-machinery semantics instead
   of an object dict (the reference proves its stack against
   K3s-in-docker, ``LocalK3sContainer.java``; no container runtime exists
   in this image).
2. **The mini-cluster's API server** (``cli mini up``): the process-kubelet
   (:mod:`langstream_tpu.k8s.kubelet`) runs pods as subprocesses that
   reach this server over real HTTP (``LS_KUBE_API_URL``) — the embedded
   role k3s's API server plays in ``mini-langstream``.

This server implements the API-machinery semantics those layers depend on,
independently of the client code under test:

- resource paths (``/api/v1``, ``/apis/<group>/<version>``, namespaced and
  cluster-scoped) for every kind in ``KIND_ROUTES``;
- a single monotonically increasing ``resourceVersion`` assigned on every
  write; **update with a stale resourceVersion → 409 Conflict**; create of
  an existing object → 409 AlreadyExists; missing object → 404 with a
  ``Status`` body;
- creates of namespaced objects **require the namespace object to exist**
  (404 NotFound otherwise) — the store's tenant-namespace lifecycle is real
  behavior, not convention;
- the ``/status`` subresource: status PUTs never touch spec, spec PUTs
  never touch status (the CRDs declare the subresource);
- ``?watch=true`` with chunked transfer: ADDED/MODIFIED/DELETED events in
  write order, starting after the client's ``resourceVersion``;
- ``labelSelector`` equality filtering on lists.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from langstream_tpu.k8s.client import KIND_ROUTES

# (prefix, plural) -> kind
_ROUTE_INDEX = {
    (prefix, plural): kind
    for kind, (prefix, plural, _ns) in KIND_ROUTES.items()
}


def _status_body(code: int, reason: str, message: str) -> bytes:
    return json.dumps({
        "kind": "Status", "apiVersion": "v1", "status": "Failure",
        "message": message, "reason": reason, "code": code,
    }).encode()


class _State:
    def __init__(self) -> None:
        self.lock = threading.Condition()
        self.objects: dict[tuple[str, str | None, str], dict] = {}
        self.rv = 0
        # (rv, event type, kind, snapshot) in write order, for watches
        self.events: list[tuple[int, str, str, dict]] = []

    def next_rv(self) -> int:
        self.rv += 1
        return self.rv

    def record(self, event: str, kind: str, obj: dict) -> None:
        self.events.append((int(obj["metadata"]["resourceVersion"]),
                            event, kind, json.loads(json.dumps(obj))))
        self.lock.notify_all()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "FakeKube/1.0"

    # -- plumbing ----------------------------------------------------------

    def log_message(self, *args):  # quiet
        pass

    @property
    def state(self) -> _State:
        return self.server.state  # type: ignore[attr-defined]

    def _send_json(self, code: int, payload: dict | bytes) -> None:
        body = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _err(self, code: int, reason: str, message: str) -> None:
        self._send_json(code, _status_body(code, reason, message))

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", "0"))
        return json.loads(self.rfile.read(length)) if length else {}

    def _route(self):
        """path → (kind, namespace, name, subresource) or None."""
        parsed = urllib.parse.urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        query = urllib.parse.parse_qs(parsed.query)
        # /api/v1/... or /apis/<group>/<version>/...
        if parts[:2] == ["api", "v1"]:
            prefix, rest = "/api/v1", parts[2:]
        elif parts[0] == "apis" and len(parts) >= 3:
            prefix, rest = f"/apis/{parts[1]}/{parts[2]}", parts[3:]
        else:
            return None
        namespace = None
        # "/namespaces/<ns>/<plural>/..." is a namespaced path ONLY when a
        # known plural follows the namespace — otherwise the path IS the
        # cluster-scoped Namespace collection (/api/v1/namespaces[/name])
        if (
            len(rest) >= 3
            and rest[0] == "namespaces"
            and (prefix, rest[2]) in _ROUTE_INDEX
        ):
            namespace, rest = rest[1], rest[2:]
        if not rest:
            return None
        kind = _ROUTE_INDEX.get((prefix, rest[0]))
        if kind is None:
            return None
        name = rest[1] if len(rest) >= 2 else None
        sub = rest[2] if len(rest) >= 3 else None
        return kind, namespace, name, sub, query

    def _key(self, kind: str, namespace: str | None, name: str):
        namespaced = KIND_ROUTES[kind][2]
        return (kind, namespace if namespaced else None, name)

    # -- verbs -------------------------------------------------------------

    def do_GET(self):  # noqa: N802
        route = self._route()
        if route is None:
            return self._err(404, "NotFound", f"no route for {self.path}")
        kind, ns, name, _sub, query = route
        if name is None:
            if query.get("watch", ["false"])[0] == "true":
                return self._watch(kind, ns, query)
            return self._list(kind, ns, query)
        with self.state.lock:
            obj = self.state.objects.get(self._key(kind, ns, name))
        if obj is None:
            return self._err(404, "NotFound", f"{kind} {name!r} not found")
        self._send_json(200, obj)

    def _list(self, kind: str, ns: str | None, query) -> None:
        selector = {}
        for part in query.get("labelSelector", [""])[0].split(","):
            if "=" in part:
                k, _, v = part.partition("=")
                selector[k] = v
        items = []
        with self.state.lock:
            for (k, ons, _n), obj in self.state.objects.items():
                if k != kind:
                    continue
                if ns is not None and ons != ns:
                    continue
                labels = (obj.get("metadata") or {}).get("labels") or {}
                if all(labels.get(sk) == sv for sk, sv in selector.items()):
                    items.append(obj)
            rv = self.state.rv
        self._send_json(200, {
            "kind": f"{kind}List", "apiVersion": "v1",
            "metadata": {"resourceVersion": str(rv)}, "items": items,
        })

    def _watch(self, kind: str, ns: str | None, query) -> None:
        since = int(query.get("resourceVersion", ["0"])[0] or 0)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def _chunk(data: bytes) -> None:
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        sent = since
        deadline = time.monotonic() + float(
            query.get("timeoutSeconds", ["30"])[0]
        )
        try:
            while time.monotonic() < deadline:
                with self.state.lock:
                    pending = [
                        (rv, ev, obj)
                        for rv, ev, k, obj in self.state.events
                        if rv > sent and k == kind
                        and (ns is None or (obj["metadata"].get("namespace") == ns))
                    ]
                    if not pending:
                        self.state.lock.wait(timeout=0.2)
                        continue
                for rv, ev, obj in pending:
                    _chunk(json.dumps({"type": ev, "object": obj}).encode() + b"\n")
                    sent = rv
            _chunk(b"")  # terminating chunk
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_POST(self):  # noqa: N802
        route = self._route()
        if route is None:
            return self._err(404, "NotFound", f"no route for {self.path}")
        kind, ns, name, _sub, _q = route
        if name is not None:
            return self._err(405, "MethodNotAllowed", "POST to an item")
        obj = self._read_body()
        meta = obj.setdefault("metadata", {})
        if KIND_ROUTES[kind][2]:
            meta.setdefault("namespace", ns)
        with self.state.lock:
            if KIND_ROUTES[kind][2]:
                ns_key = ("Namespace", None, meta.get("namespace") or "")
                if ns_key not in self.state.objects:
                    return self._err(
                        404, "NotFound",
                        f"namespace {meta.get('namespace')!r} not found",
                    )
            key = self._key(kind, meta.get("namespace"), meta["name"])
            if key in self.state.objects:
                return self._err(
                    409, "AlreadyExists", f"{kind} {meta['name']!r} exists"
                )
            meta["resourceVersion"] = str(self.state.next_rv())
            meta.setdefault("uid", str(uuid.uuid4()))
            meta.setdefault("creationTimestamp", "2026-01-01T00:00:00Z")
            self.state.objects[key] = json.loads(json.dumps(obj))
            self.state.record("ADDED", kind, self.state.objects[key])
            self._send_json(201, self.state.objects[key])

    def do_PUT(self):  # noqa: N802
        route = self._route()
        if route is None:
            return self._err(404, "NotFound", f"no route for {self.path}")
        kind, ns, name, sub, _q = route
        if name is None:
            return self._err(405, "MethodNotAllowed", "PUT needs a name")
        obj = self._read_body()
        with self.state.lock:
            key = self._key(kind, ns, name)
            existing = self.state.objects.get(key)
            if existing is None:
                return self._err(404, "NotFound", f"{kind} {name!r} not found")
            claimed = (obj.get("metadata") or {}).get("resourceVersion")
            current = existing["metadata"]["resourceVersion"]
            if claimed is not None and str(claimed) != str(current):
                # the heart of optimistic concurrency: a stale writer loses
                return self._err(
                    409, "Conflict",
                    f"Operation cannot be fulfilled on {kind} {name!r}: "
                    f"object was modified (have {current}, got {claimed})",
                )
            merged = json.loads(json.dumps(obj))
            merged.setdefault("metadata", {})["namespace"] = existing[
                "metadata"].get("namespace")
            merged["metadata"]["uid"] = existing["metadata"]["uid"]
            if sub == "status":
                # status subresource: ONLY status moves
                merged = json.loads(json.dumps(existing))
                merged["status"] = obj.get("status") or {}
            else:
                # main resource: status is owned by the subresource
                if "status" in existing:
                    merged["status"] = existing["status"]
                merged.setdefault("kind", kind)
            merged["metadata"]["resourceVersion"] = str(self.state.next_rv())
            self.state.objects[key] = merged
            self.state.record("MODIFIED", kind, merged)
            self._send_json(200, merged)

    def do_DELETE(self):  # noqa: N802
        route = self._route()
        if route is None:
            return self._err(404, "NotFound", f"no route for {self.path}")
        kind, ns, name, _sub, _q = route
        if name is None:
            return self._err(405, "MethodNotAllowed", "collection delete unsupported")
        with self.state.lock:
            key = self._key(kind, ns, name)
            existing = self.state.objects.pop(key, None)
            if existing is None:
                return self._err(404, "NotFound", f"{kind} {name!r} not found")
            existing["metadata"]["resourceVersion"] = str(self.state.next_rv())
            self.state.record("DELETED", kind, existing)
            self._cascade(existing["metadata"].get("uid"))
            self._send_json(200, existing)

    def _cascade(self, owner_uid: str | None) -> None:
        """Server-side garbage collection: objects owner-referencing a
        deleted uid go too (what the real GC controller does; the operator
        stamps StatefulSets/Services with their Agent CR as owner).
        Caller holds the state lock."""
        if not owner_uid:
            return
        doomed = [
            (key, obj) for key, obj in self.state.objects.items()
            if any(
                ref.get("uid") == owner_uid
                for ref in (obj.get("metadata") or {}).get("ownerReferences", [])
            )
        ]
        for key, obj in doomed:
            del self.state.objects[key]
            obj["metadata"]["resourceVersion"] = str(self.state.next_rv())
            self.state.record("DELETED", key[0], obj)
            self._cascade(obj["metadata"].get("uid"))


class FakeKubeApiServer:
    """Run the fake API server on an ephemeral localhost port."""

    def __init__(self) -> None:
        self.state = _State()
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.port = 0

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "FakeKubeApiServer":
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        self._httpd.state = self.state  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def __enter__(self) -> "FakeKubeApiServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
