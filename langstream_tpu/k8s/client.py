"""Kubernetes API access: a thin typed-by-kind client facade.

Parity: ``langstream-k8s-common`` (shared fabric8 client factory +
``KubeTestServer`` mock). Everything above (deployer, operator, stores) codes
against :class:`KubeApi`; tests and the dev-mode runner use
:class:`InMemoryKubeApi` (the ``KubeTestServer`` role), real clusters use
:class:`HttpKubeApi` — stdlib-only (urllib + in-cluster service-account
auth), since no kubernetes client library is baked into the image.
"""

from __future__ import annotations

import json
import ssl
import urllib.error
import urllib.request
from pathlib import Path
from typing import Any, Callable

# kind → (api prefix, plural, namespaced)
KIND_ROUTES: dict[str, tuple[str, str, bool]] = {
    "Application": ("/apis/langstream.tpu/v1alpha1", "applications", True),
    "Agent": ("/apis/langstream.tpu/v1alpha1", "agents", True),
    "Secret": ("/api/v1", "secrets", True),
    "ConfigMap": ("/api/v1", "configmaps", True),
    "Service": ("/api/v1", "services", True),
    "Pod": ("/api/v1", "pods", True),
    "PersistentVolumeClaim": ("/api/v1", "persistentvolumeclaims", True),
    "Namespace": ("/api/v1", "namespaces", False),
    "StatefulSet": ("/apis/apps/v1", "statefulsets", True),
    "Job": ("/apis/batch/v1", "jobs", True),
    "PodDisruptionBudget": ("/apis/policy/v1", "poddisruptionbudgets", True),
    "CustomResourceDefinition": (
        "/apis/apiextensions.k8s.io/v1",
        "customresourcedefinitions",
        False,
    ),
}


class KubeConflictError(RuntimeError):
    """409 from the API server: optimistic-concurrency loss (stale
    resourceVersion) or create of an existing object."""


class KubeNotFoundError(RuntimeError):
    """404 on a write: the target (or its namespace) does not exist."""


class KubeApi:
    """Minimal CRUD surface the control/data-plane layers need."""

    def get(self, kind: str, namespace: str | None, name: str) -> dict | None:
        raise NotImplementedError

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict]:
        raise NotImplementedError

    def apply(self, obj: dict) -> dict:
        """Create-or-replace by (kind, namespace, name)."""
        raise NotImplementedError

    def delete(self, kind: str, namespace: str | None, name: str) -> bool:
        raise NotImplementedError

    def update_status(self, obj: dict) -> dict:
        raise NotImplementedError

    # convenience
    def exists(self, kind: str, namespace: str | None, name: str) -> bool:
        return self.get(kind, namespace, name) is not None


def _match_labels(obj: dict, selector: dict[str, str] | None) -> bool:
    if not selector:
        return True
    labels = (obj.get("metadata") or {}).get("labels") or {}
    return all(labels.get(k) == v for k, v in selector.items())


class InMemoryKubeApi(KubeApi):
    """The fake API server used by tests and `docker run` dev mode.

    Keeps every applied object; records mutations in ``events`` so tests can
    assert on CR writes the way the reference's ``KubeTestServer`` spies do.
    Optional ``on_apply`` hooks let tests simulate controller behavior
    (e.g. marking StatefulSets ready).
    """

    def __init__(self) -> None:
        self.objects: dict[tuple[str, str | None, str], dict] = {}
        self.events: list[tuple[str, str, str | None, str]] = []  # op, kind, ns, name
        self.on_apply: list[Callable[[dict], None]] = []

    def _key(self, kind: str, namespace: str | None, name: str):
        if kind not in KIND_ROUTES:
            raise ValueError(f"unknown kind {kind!r}")
        namespaced = KIND_ROUTES[kind][2]
        return (kind, namespace if namespaced else None, name)

    def get(self, kind: str, namespace: str | None, name: str) -> dict | None:
        obj = self.objects.get(self._key(kind, namespace, name))
        return json.loads(json.dumps(obj)) if obj is not None else None

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict]:
        out = []
        for (k, ns, _), obj in self.objects.items():
            if k != kind:
                continue
            if namespace is not None and ns != namespace:
                continue
            if _match_labels(obj, label_selector):
                out.append(json.loads(json.dumps(obj)))
        return out

    def apply(self, obj: dict) -> dict:
        kind = obj["kind"]
        meta = obj.get("metadata") or {}
        key = self._key(kind, meta.get("namespace"), meta["name"])
        existing = self.objects.get(key)
        if existing is not None and "status" not in obj and "status" in existing:
            obj = {**obj, "status": existing["status"]}
        obj = json.loads(json.dumps(obj))
        # stable uid across updates (owner references point at it)
        if existing is not None and existing.get("metadata", {}).get("uid"):
            obj.setdefault("metadata", {})["uid"] = existing["metadata"]["uid"]
        else:
            import uuid

            obj.setdefault("metadata", {}).setdefault("uid", str(uuid.uuid4()))
        self.objects[key] = obj
        self.events.append(
            ("apply", kind, meta.get("namespace"), meta["name"])
        )
        for hook in self.on_apply:
            hook(self.objects[key])
        return self.get(kind, meta.get("namespace"), meta["name"])

    def delete(self, kind: str, namespace: str | None, name: str) -> bool:
        key = self._key(kind, namespace, name)
        removed = self.objects.pop(key, None)
        if removed is None:
            return False
        self.events.append(("delete", kind, namespace, name))
        # garbage-collect dependents (what the real API server's GC
        # controller does for ownerReferences; dev mode matches clusters)
        uid = (removed.get("metadata") or {}).get("uid")
        if uid:
            doomed = [
                (k, ns, n)
                for (k, ns, n), o in list(self.objects.items())
                if any(
                    ref.get("uid") == uid
                    for ref in (o.get("metadata") or {}).get(
                        "ownerReferences", []
                    )
                )
            ]
            for k, ns, n in doomed:
                self.delete(k, ns, n)
        return True

    def update_status(self, obj: dict) -> dict:
        kind = obj["kind"]
        meta = obj.get("metadata") or {}
        key = self._key(kind, meta.get("namespace"), meta["name"])
        if key not in self.objects:
            raise KeyError(f"{kind}/{meta['name']} not found")
        self.objects[key]["status"] = json.loads(json.dumps(obj.get("status") or {}))
        self.events.append(("status", kind, meta.get("namespace"), meta["name"]))
        return self.get(kind, meta.get("namespace"), meta["name"])

    # test helpers (KubeTestServer.spyAgentCustomResources role)
    def applied(self, kind: str) -> list[str]:
        return [n for op, k, _, n in self.events if op == "apply" and k == kind]


class HttpKubeApi(KubeApi):
    """Real API server over stdlib HTTP.

    In-cluster: reads the service-account token + CA from the standard
    mount; out-of-cluster: pass ``base_url``/``token``/``ca_file`` directly.
    """

    SA_DIR = Path("/var/run/secrets/kubernetes.io/serviceaccount")

    def __init__(
        self,
        base_url: str,
        token: str | None = None,
        ca_file: str | None = None,
        insecure: bool = False,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        if insecure:
            self.ssl_context = ssl._create_unverified_context()
        elif ca_file:
            self.ssl_context = ssl.create_default_context(cafile=ca_file)
        else:
            self.ssl_context = ssl.create_default_context()

    @classmethod
    def in_cluster(cls) -> "HttpKubeApi":
        import os

        # mini-cluster lane: process-pods (k8s/kubelet.py) are plain OS
        # processes, not containers — the kubelet hands them the API
        # server address directly instead of a service-account mount
        override = os.environ.get("LS_KUBE_API_URL")
        if override:
            return cls(override, token=os.environ.get("LS_KUBE_API_TOKEN"))
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        token = (cls.SA_DIR / "token").read_text().strip()
        return cls(
            f"https://{host}:{port}",
            token=token,
            ca_file=str(cls.SA_DIR / "ca.crt"),
        )

    def _url(self, kind: str, namespace: str | None, name: str | None = None) -> str:
        prefix, plural, namespaced = KIND_ROUTES[kind]
        parts = [self.base_url, prefix.lstrip("/")]
        if namespaced and namespace:
            parts += ["namespaces", namespace]
        parts.append(plural)
        if name:
            parts.append(name)
        return "/".join(parts)

    def _request(
        self, method: str, url: str, body: dict | None = None
    ) -> dict | None:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            context = self.ssl_context if url.startswith("https") else None
            with urllib.request.urlopen(req, context=context) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else None
        except urllib.error.HTTPError as e:
            if e.code == 404 and method in ("GET", "DELETE"):
                # object absence is an answer for reads/deletes; for a
                # create/update a 404 is a real failure (e.g. the target
                # namespace does not exist) and must not vanish into None
                return None
            detail = e.read()[:500]
            if e.code == 404:
                raise KubeNotFoundError(
                    f"kube api {method} {url}: 404 {detail!r}"
                ) from e
            if e.code == 409:
                raise KubeConflictError(
                    f"kube api {method} {url}: 409 {detail!r}"
                ) from e
            raise RuntimeError(
                f"kube api {method} {url} failed: {e.code} {detail!r}"
            ) from e

    def get(self, kind: str, namespace: str | None, name: str) -> dict | None:
        return self._request("GET", self._url(kind, namespace, name))

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict]:
        url = self._url(kind, namespace)
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in label_selector.items())
            url += f"?labelSelector={urllib.request.quote(sel)}"
        result = self._request("GET", url) or {}
        return result.get("items", [])

    RETRIES = 5

    def apply(self, obj: dict) -> dict:
        """Create-or-replace with optimistic-concurrency retries: a 409
        (another writer bumped resourceVersion between our GET and PUT, or
        created the object before our POST) re-reads and retries — the
        level-triggered reconcilers re-derive the full desired state, so
        last-writer-wins on the spec is the correct outcome."""
        kind = obj["kind"]
        meta = obj["metadata"]
        namespace, name = meta.get("namespace"), meta["name"]
        for _ in range(self.RETRIES):
            existing = self.get(kind, namespace, name)
            if existing is None:
                try:
                    return self._request("POST", self._url(kind, namespace), obj)
                except KubeConflictError:
                    continue  # created concurrently: retry as an update
                # a POST 404 (missing namespace) is permanent — let the
                # KubeNotFoundError propagate, retrying cannot fix it
            try:
                # deep-copy before injecting resourceVersion: the caller's
                # manifest must stay reusable (a stale resourceVersion
                # poisons later applies)
                candidate = json.loads(json.dumps(obj))
                candidate.setdefault("metadata", {})["resourceVersion"] = (
                    existing["metadata"]["resourceVersion"]
                )
                return self._request(
                    "PUT", self._url(kind, namespace, name), candidate
                )
            except KubeNotFoundError:
                continue  # deleted underneath us: retry as a create
            except KubeConflictError:
                continue
        raise KubeConflictError(
            f"apply of {kind}/{name} kept conflicting after "
            f"{self.RETRIES} attempts"
        )

    def delete(self, kind: str, namespace: str | None, name: str) -> bool:
        return (
            self._request("DELETE", self._url(kind, namespace, name)) is not None
        )

    def update_status(self, obj: dict) -> dict:
        kind = obj["kind"]
        meta = obj["metadata"]
        url = self._url(kind, meta.get("namespace"), meta["name"]) + "/status"
        for _ in range(self.RETRIES):
            current = self.get(kind, meta.get("namespace"), meta["name"])
            if current is None:
                raise KeyError(f"{kind}/{meta['name']} not found")
            merged = {**current, "status": obj.get("status") or {}}
            try:
                return self._request("PUT", url, merged)
            except KubeNotFoundError:
                raise KeyError(f"{kind}/{meta['name']} not found") from None
            except KubeConflictError:
                continue
        raise KubeConflictError(
            f"status update of {kind}/{meta['name']} kept conflicting "
            f"after {self.RETRIES} attempts"
        )

    def watch(
        self,
        kind: str,
        namespace: str | None = None,
        resource_version: str | None = None,
        timeout_s: float = 30.0,
    ):
        """Yield ``(event_type, object)`` from a server watch stream
        (ADDED/MODIFIED/DELETED) until the server closes it — the
        level-triggered poll loop's wake-up signal, not a state store."""
        url = self._url(kind, namespace)
        params = {"watch": "true", "timeoutSeconds": str(int(timeout_s))}
        if resource_version is not None:
            params["resourceVersion"] = str(resource_version)
        url += "?" + "&".join(f"{k}={v}" for k, v in params.items())
        req = urllib.request.Request(url, method="GET")
        req.add_header("Accept", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        context = self.ssl_context if url.startswith("https") else None
        with urllib.request.urlopen(
            req, context=context, timeout=timeout_s + 10
        ) as resp:
            for line in resp:
                line = line.strip()
                if not line:
                    continue
                event = json.loads(line)
                yield event.get("type"), event.get("object")
