"""Compute-cluster runtime for Kubernetes.

Parity: ``KubernetesClusterRuntime``
(``langstream-k8s-runtime/.../k8s/KubernetesClusterRuntime.java:55,93,394``):
``deploy`` converts an :class:`ExecutionPlan` into one Agent CR + one
agent-config Secret per agent node in the tenant namespace
(``langstream-<tenant>``); ``delete`` removes them. The operator
(:mod:`langstream_tpu.k8s.operator`) reconciles the CRs into StatefulSets.
"""

from __future__ import annotations

import base64
import json
from typing import Any

from langstream_tpu.api.execution_plan import ExecutionPlan
from langstream_tpu.k8s.client import KubeApi
from langstream_tpu.k8s.crds import (
    AgentCustomResource,
    AgentResourcesCR,
    AgentSpec,
    DiskSpecCR,
    config_checksum,
)
from langstream_tpu.k8s.podconfig import pod_configuration
from langstream_tpu.k8s.resources import AgentResourcesFactory

DEFAULT_IMAGE = "langstream-tpu/runtime:latest"


def tenant_namespace(tenant: str) -> str:
    return f"langstream-{tenant}"


class KubernetesClusterRuntime:
    def __init__(
        self,
        api: KubeApi,
        image: str = DEFAULT_IMAGE,
        code_storage: dict[str, Any] | None = None,
    ):
        self.api = api
        self.image = image
        # code-storage client config shipped to every pod so the
        # agent-code-download init container can pull the archive
        self.code_storage = code_storage or {}

    def deploy(
        self, tenant: str, plan: ExecutionPlan, code_archive_id: str | None = None
    ) -> list[AgentCustomResource]:
        namespace = tenant_namespace(tenant)
        crs: list[AgentCustomResource] = []
        for node in plan.agents.values():
            config = pod_configuration(plan, node)
            config["tenant"] = tenant
            if code_archive_id:
                config["codeArchiveId"] = code_archive_id
                config["codeStorage"] = {
                    **self.code_storage,
                    "codeArchiveId": code_archive_id,
                }
            checksum = config_checksum(config)
            name = AgentResourcesFactory.agent_resource_name(
                plan.application_id, node.id
            )
            secret_name = f"{name}-config"
            self.api.apply(
                {
                    "apiVersion": "v1",
                    "kind": "Secret",
                    "metadata": {
                        "name": secret_name,
                        "namespace": namespace,
                        "labels": {
                            "langstream-application": plan.application_id,
                            "langstream-agent": node.id,
                        },
                    },
                    "data": {
                        "config": base64.b64encode(
                            json.dumps(config).encode()
                        ).decode()
                    },
                }
            )
            disk = node.resources.disk
            # disaggregated serving pools (docs/DISAGG.md): an agent
            # whose configuration declares `pool-roles` splits into one
            # StatefulSet per role (the manifest factory reads the CR
            # option; pods learn their role via LS_POOL_ROLE)
            node_cfg = getattr(node, "configuration", None) or {}
            pool_roles = node_cfg.get("pool-roles") or node_cfg.get(
                "pool_roles"
            )
            options: dict[str, Any] = {"codeArchiveId": code_archive_id}
            if pool_roles:
                options["poolRoles"] = pool_roles
            cr = AgentCustomResource(
                name=name,
                namespace=namespace,
                spec=AgentSpec(
                    tenant=tenant,
                    application_id=plan.application_id,
                    agent_id=node.id,
                    image=self.image,
                    agent_config_secret_ref=secret_name,
                    agent_config_secret_ref_checksum=checksum,
                    resources=AgentResourcesCR(
                        parallelism=node.resources.parallelism,
                        size=node.resources.size,
                        device_mesh=node.resources.device_mesh,
                    ),
                    disk=(
                        DiskSpecCR(
                            enabled=disk.enabled, size=disk.size, type=disk.type
                        )
                        if disk
                        else None
                    ),
                    options=options,
                ),
            )
            self.api.apply(cr.to_dict())
            crs.append(cr)
        # prune agents dropped from the plan (a redeploy that removes a
        # pipeline step must tear its pods down, not leak them)
        wanted = {cr.name for cr in crs}
        for existing in self.current_agents(tenant, plan.application_id):
            name = existing["metadata"]["name"]
            if name not in wanted:
                self.api.delete("Agent", namespace, name)
                self.api.delete("Secret", namespace, f"{name}-config")
        return crs

    def delete(self, tenant: str, plan: ExecutionPlan) -> None:
        namespace = tenant_namespace(tenant)
        for node in plan.agents.values():
            name = AgentResourcesFactory.agent_resource_name(
                plan.application_id, node.id
            )
            self.api.delete("Agent", namespace, name)
            self.api.delete("Secret", namespace, f"{name}-config")

    def current_agents(self, tenant: str, application_id: str) -> list[dict[str, Any]]:
        return self.api.list(
            "Agent",
            tenant_namespace(tenant),
            label_selector={"langstream-application": application_id},
        )
