"""Kubernetes compute runtime for the control plane.

The in-cluster twin of
:class:`langstream_tpu.controlplane.server.LocalComputeRuntime` (same
duck-typed interface the ControlPlaneServer drives: ``deploy`` /
``undeploy`` / ``agent_info`` / ``logs`` / ``close``): instead of running
agents in-process, it plans the application and writes Agent custom
resources + config Secrets for the operator to reconcile into
StatefulSets — the role the reference's webservice plays against
``langstream-k8s-deployer`` (``ApplicationLifecycleService`` →
``AppResourcesFactory``).
"""

from __future__ import annotations

import logging
from collections import deque
from pathlib import Path
from typing import Any

from langstream_tpu.controlplane.stores import StoredApplication
from langstream_tpu.core.codestorage import make_code_storage, zip_directory
from langstream_tpu.core.deployer import ApplicationDeployer
from langstream_tpu.k8s.client import KubeApi
from langstream_tpu.k8s.cluster_runtime import KubernetesClusterRuntime

log = logging.getLogger(__name__)


class KubernetesComputeRuntime:
    """Plans apps and manages their Agent CRs in the cluster."""

    def __init__(
        self,
        api: KubeApi,
        image: str = "langstream-tpu/runtime:latest",
        code_storage_config: dict[str, Any] | None = None,
        pods_root: Path | str | None = None,
    ):
        self.api = api
        # the ProcessKubelet root: pod subprocess stdout/stderr lands in
        # <pods_root>/pods/<namespace>/<pod>/pod.log, which /logs surfaces
        self.pods_root = Path(pods_root) if pods_root is not None else None
        self.code_storage_config = code_storage_config
        self.code_storage = (
            make_code_storage(code_storage_config) if code_storage_config else None
        )
        self.runtime = KubernetesClusterRuntime(
            api, image=image, code_storage=code_storage_config
        )
        self.deployer = ApplicationDeployer()
        self.logs: dict[tuple[str, str], deque[str]] = {}
        self._plans: dict[tuple[str, str], Any] = {}

    def append_log(self, tenant: str, name: str, line: str) -> None:
        self.logs.setdefault((tenant, name), deque(maxlen=1000)).append(line)

    async def deploy(self, stored: StoredApplication, application=None) -> None:
        from langstream_tpu.controlplane.server import parse_stored

        if application is None:
            application = parse_stored(stored)
        key = (stored.tenant, stored.name)
        plan = self.deployer.create_implementation(stored.name, application)
        await self.deployer.setup(plan)

        code_archive_id = None
        if self.code_storage is not None:
            # ship the application package so agent pods' init containers
            # can download custom-agent code
            import io
            import zipfile

            buf = io.BytesIO()
            with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
                for filename, content in stored.files.items():
                    zf.writestr(f"app/{filename}", content)
            code_archive_id = self.code_storage.store(
                stored.tenant, stored.name, buf.getvalue()
            )
        # stamp the archive onto the stored app: the caller's follow-up
        # put_application persists it into the Application CR, and the
        # operator's deployer Job then writes byte-identical Agent CRs
        stored.code_archive_id = code_archive_id
        crs = self.runtime.deploy(stored.tenant, plan, code_archive_id)
        self._plans[key] = plan
        self.append_log(
            *key, f"wrote {len(crs)} agent CRs (operator reconciles them)"
        )

    async def undeploy(self, tenant: str, name: str) -> None:
        from langstream_tpu.k8s.cluster_runtime import tenant_namespace

        key = (tenant, name)
        plan = self._plans.pop(key, None)
        if plan is not None:
            self.runtime.delete(tenant, plan)
        else:
            # control plane restarted since deploy: delete by listing the
            # application's live CRs instead of re-planning
            namespace = tenant_namespace(tenant)
            for existing in self.runtime.current_agents(tenant, name):
                cr_name = existing["metadata"]["name"]
                self.api.delete("Agent", namespace, cr_name)
                self.api.delete("Secret", namespace, f"{cr_name}-config")
        self.logs.pop(key, None)

    def pod_logs(
        self, tenant: str, name: str, tail: int = 200
    ) -> dict[str, list[str]]:
        """Pod name → last ``tail`` lines of its ``pod.log``.

        Pod names come from the application's live StatefulSets and Jobs,
        matched by their ``langstream-application`` label — name-prefix
        matching against kubelet directories would leak logs across
        applications whose ids prefix each other (``chat`` vs ``chat-2``).
        STS pods are ``<sts>-<ordinal>``; a Job's pod shares the Job's
        name (see ``ProcessKubelet``). Synchronous file I/O by design:
        the /logs handler offloads it to an executor.
        """
        from langstream_tpu.k8s.cluster_runtime import tenant_namespace

        if self.pods_root is None:
            return {}
        namespace = tenant_namespace(tenant)
        ns_dir = self.pods_root / "pods" / namespace
        if not ns_dir.is_dir():
            return {}
        selector = {"langstream-application": name}
        pod_names: set[str] = set()
        for sts in self.api.list(
            "StatefulSet", namespace, label_selector=selector
        ):
            sts_name = sts["metadata"]["name"]
            replicas = int(sts["spec"].get("replicas", 1))
            pod_names.update(f"{sts_name}-{i}" for i in range(replicas))
        for job in self.api.list("Job", namespace, label_selector=selector):
            pod_names.add(job["metadata"]["name"])
        out: dict[str, list[str]] = {}
        for pod_name in sorted(pod_names):
            log_path = ns_dir / pod_name / "pod.log"
            if not log_path.is_file():
                continue
            # bounded tail read: pod.log is append-only and never rotated,
            # so reading the whole file would grow without limit
            window = max(tail * 512, 65536)
            try:
                with log_path.open("rb") as f:
                    f.seek(0, 2)
                    size = f.tell()
                    f.seek(max(0, size - window))
                    chunk = f.read(window)
            except OSError:
                continue
            lines = chunk.decode(errors="replace").splitlines()
            if size > window:
                lines = lines[1:]  # window start lands mid-line; drop it
            out[pod_name] = lines[-tail:]
        return out

    def _pod_addresses(self, tenant: str, name: str) -> dict[str, str]:
        """Pod name → in-cluster base URL for the runtime's :8080 server,
        via the STS headless service (``<pod>.<service>.<ns>.svc``)."""
        from langstream_tpu.k8s.cluster_runtime import tenant_namespace
        from langstream_tpu.k8s.resources import AGENT_PORT

        namespace = tenant_namespace(tenant)
        selector = {"langstream-application": name}
        out: dict[str, str] = {}
        for sts in self.api.list(
            "StatefulSet", namespace, label_selector=selector
        ):
            sts_name = sts["metadata"]["name"]
            service = sts["spec"].get("serviceName", sts_name)
            for i in range(int(sts["spec"].get("replicas", 1))):
                pod = f"{sts_name}-{i}"
                out[pod] = (
                    f"http://{pod}.{service}.{namespace}.svc:{AGENT_PORT}"
                )
        return out

    def _pod_json_fanin(
        self, tenant: str, name: str, path: str
    ) -> list[tuple[str, Any]]:
        """(pod, parsed JSON payload) for every application pod serving
        ``path`` on its runtime HTTP port. Best-effort: an unreachable pod
        contributes ``None`` — aggregation must not 502 because one
        replica is restarting. Member-shaped aggregates (flight, qos,
        health, slo) MUST surface the ``None`` as an ``unreachable``
        member rather than dropping it (an operator reading an aggregate
        that silently omits the one pod that timed out would conclude
        the fleet is fine precisely when it is not); :meth:`traces` is
        the one exception — its payload is a span/rollup list keyed by
        trace_id with no per-pod member shape to hang the marker on.
        Non-2xx answers parse like any other body (probe
        endpoints speak JSON at 503 too). Synchronous by design (handlers
        run it in a thread); pods are fetched concurrently — serial 2 s
        timeouts against a rolling restart would cost replicas x 2 s per
        request."""
        import json as _json
        import urllib.error
        import urllib.request
        from concurrent.futures import ThreadPoolExecutor

        def _fetch(pod_base: tuple[str, str]) -> tuple[str, Any]:
            pod, base = pod_base
            try:
                with urllib.request.urlopen(base + path, timeout=2) as resp:
                    return pod, _json.loads(resp.read())
            except urllib.error.HTTPError as e:
                # the pod answered: a 503 probe body is a report, not an
                # outage — read it. The read itself can still stall/fail
                # (status line sent, body never arrives — the wedged-pod
                # shape), so OSError here means unreachable too, never a
                # 500 out of the aggregate route
                try:
                    return pod, _json.loads(e.read())
                except (OSError, ValueError):
                    log.debug("pod %s %s: unreadable %s body", pod, path, e.code)
                    return pod, None
            except (urllib.error.URLError, OSError, ValueError) as e:
                log.debug("pod %s %s unreachable: %s", pod, path, e)
                return pod, None

        pods = sorted(self._pod_addresses(tenant, name).items())
        if not pods:
            return []
        with ThreadPoolExecutor(max_workers=min(8, len(pods))) as pool:
            return list(pool.map(_fetch, pods))

    def traces(
        self, tenant: str, name: str, trace_id: str | None = None
    ) -> list[dict[str, Any]]:
        """Aggregate the application pods' ``/traces`` ring buffers (the
        same fan-in /logs does for pod.log, but over the pods' HTTP
        endpoints)."""
        path = f"/traces/{trace_id}" if trace_id else "/traces"
        merged: list[dict[str, Any]] = []
        for _pod, chunk in self._pod_json_fanin(tenant, name, path):
            if isinstance(chunk, list):
                merged.extend(chunk)
        if trace_id is None:
            # index entries are per-pod PARTIAL rollups of the same trace
            # (each agent pod buffered its own hop): merge them per
            # trace_id or a client keying by id sees duplicate rows with
            # conflicting span counts/durations
            merged = self._merge_summaries(merged)
        merged.sort(key=lambda s: s.get("start_ms", 0.0))
        return merged

    @staticmethod
    def _merge_summaries(
        partials: list[dict[str, Any]]
    ) -> list[dict[str, Any]]:
        by_trace: dict[str, dict[str, Any]] = {}
        for part in partials:
            trace_id = part.get("trace_id")
            agg = by_trace.get(trace_id)
            if agg is None:
                by_trace[trace_id] = dict(part)
                continue
            start = min(agg["start_ms"], part.get("start_ms", 0.0))
            end = max(
                agg["start_ms"] + agg.get("duration_ms", 0.0),
                part.get("start_ms", 0.0) + part.get("duration_ms", 0.0),
            )
            if part.get("start_ms", 0.0) < agg["start_ms"]:
                # root-most span name comes from the earliest partial
                agg["root"] = part.get("root")
            agg["start_ms"] = start
            agg["duration_ms"] = round(end - start, 3)
            agg["spans"] = agg.get("spans", 0) + part.get("spans", 0)
            agg["errors"] = agg.get("errors", 0) + part.get("errors", 0)
            agg["services"] = sorted(
                {*agg.get("services", []), *part.get("services", [])}
            )
        return list(by_trace.values())

    def journey(
        self, tenant: str, name: str, journey_id: str
    ) -> dict[str, Any]:
        """Stitch one request's journey across the application's pods
        (the ``/api/applications/{t}/{n}/journey/{id}`` route): each pod
        serves its PARTIAL event ledger on ``/journey/{id}``, and the
        merge orders every pod's edges into one timeline with its
        segment decomposition — the disaggregated case is the point
        (prefill pod, decode pod, and any bounced replica each hold a
        partial; docs/OBSERVABILITY.md "Request journey plane"). Events
        are tagged with their pod before stitching so the waterfall
        names where each edge happened. Unreachable pods simply
        contribute nothing — a partial timeline with a flagged gap
        beats a 502."""
        from langstream_tpu.serving.journey import stitch

        partials: list[list[dict[str, Any]]] = []
        for pod, chunk in self._pod_json_fanin(
            tenant, name, f"/journey/{journey_id}"
        ):
            if isinstance(chunk, list) and chunk:
                partials.append(
                    [
                        {"pod": pod, **event}
                        for event in chunk
                        if isinstance(event, dict)
                    ]
                )
        if not partials:
            return {}
        return stitch(journey_id, partials)

    def flight(self, tenant: str, name: str) -> list[dict[str, Any]]:
        """Fan in the application pods' ``/flight`` reports. Unlike traces
        (one logical trace spans pods, so partial rollups merge), a flight
        entry is one engine on one pod — entries concatenate, each tagged
        with its pod so ``engine_top`` and operators can tell replicas
        apart. A pod whose fetch timed out appears as an ``unreachable``
        member: during an incident the missing replica IS the signal, and
        silently dropping it made the aggregate read healthy exactly when
        a pod hung."""
        merged: list[dict[str, Any]] = []
        for pod, chunk in self._pod_json_fanin(tenant, name, "/flight"):
            if chunk is None:
                merged.append({"pod": pod, "unreachable": True})
                continue
            for entry in chunk if isinstance(chunk, list) else []:
                if isinstance(entry, dict):
                    merged.append({"pod": pod, **entry})
        return merged

    def attribution(self, tenant: str, name: str) -> list[dict[str, Any]]:
        """Fan in the application pods' ``/attribution`` payloads —
        device attribution (per-program cost ledger + HBM memory
        ledger) concatenates per engine per pod exactly like
        :meth:`flight`, with timed-out pods surfaced as ``unreachable``
        members, never dropped."""
        merged: list[dict[str, Any]] = []
        for pod, chunk in self._pod_json_fanin(tenant, name, "/attribution"):
            if chunk is None:
                merged.append({"pod": pod, "unreachable": True})
                continue
            for entry in chunk if isinstance(chunk, list) else []:
                if isinstance(entry, dict):
                    merged.append({"pod": pod, **entry})
        return merged

    def incidents(
        self, tenant: str, name: str, bundle_id: str | None = None
    ) -> list[dict[str, Any]]:
        """Fan in the application pods' ``/incidents`` payloads — the
        bounded breach-bundle index per engine per pod (or one full
        bundle by id), concatenated exactly like :meth:`flight`, with
        timed-out pods surfaced as ``unreachable`` members: during an
        incident the replica that stopped answering is evidence, not
        noise."""
        path = "/incidents" + (f"/{bundle_id}" if bundle_id else "")
        merged: list[dict[str, Any]] = []
        for pod, chunk in self._pod_json_fanin(tenant, name, path):
            if chunk is None:
                if bundle_id is None:
                    merged.append({"pod": pod, "unreachable": True})
                continue
            for entry in chunk if isinstance(chunk, list) else []:
                if isinstance(entry, dict):
                    merged.append({"pod": pod, **entry})
        return merged

    def _summary_section_fanin(
        self, tenant: str, name: str, section: str
    ) -> dict[str, Any]:
        """Shared shape of the qos/slo aggregates: fan in the pods'
        ``/flight/summary`` entries and keep one ``section`` per engine,
        tagged per pod like :meth:`flight`; timed-out pods surface as
        ``unreachable`` members. The declared policy lives in the stored
        application (the control plane serves it from the app files), so
        ``configured`` stays empty here — the dev-mode runtime fills
        it."""
        engines: list[dict[str, Any]] = []
        for pod, chunk in self._pod_json_fanin(tenant, name, "/flight/summary"):
            if chunk is None:
                engines.append({"pod": pod, "unreachable": True})
                continue
            for entry in chunk if isinstance(chunk, list) else []:
                if isinstance(entry, dict):
                    engines.append(
                        {
                            "pod": pod,
                            "model": entry.get("model"),
                            section: entry.get(section),
                        }
                    )
        return {"configured": {}, "engines": engines}

    def qos(self, tenant: str, name: str) -> dict[str, Any]:
        """QoS status: the per-engine ``scheduler`` sections (per-class
        queued/admitted/shed/preempted counters + tenant throttles) off
        ``/flight/summary`` — the engine exposes no dedicated QoS
        endpoint by design."""
        return self._summary_section_fanin(tenant, name, "scheduler")

    def health(self, tenant: str, name: str) -> dict[str, Any]:
        """Fleet health: fan in the pods' ``/healthz`` verdicts (each a
        dict — status + per-engine watchdog sections, runtime/pod.py) and
        aggregate worst-state. Unreachable pods are first-class members,
        ranked ``degraded`` for the aggregate: a pod that cannot answer
        its own health probe may be restarting (routine) or hung (the
        r03 shape) — the member entry carries the evidence either way,
        and its own liveness probe is what escalates a hang to a
        reschedule."""
        from langstream_tpu.serving.health import worst_state

        pods: list[dict[str, Any]] = []
        states: list[str] = []
        for pod, payload in self._pod_json_fanin(tenant, name, "/healthz"):
            if not isinstance(payload, dict):
                pods.append({"pod": pod, "unreachable": True})
                states.append("degraded")
                continue
            pods.append({"pod": pod, **payload})
            states.append(payload.get("status", "wedged"))
        return {"status": worst_state(states), "pods": pods}

    def slo(self, tenant: str, name: str) -> dict[str, Any]:
        """SLO status: the per-engine ``slo`` sections (burn rates,
        budget remaining, alerting objectives) off ``/flight/summary``."""
        return self._summary_section_fanin(tenant, name, "slo")

    def agent_info(self, tenant: str, name: str) -> list[dict[str, Any]]:
        """Agent CR specs + operator-written statuses."""
        return [
            {
                "agent-id": cr["spec"].get("agentId"),
                "type": "k8s-agent",
                "status": cr.get("status", {}),
                "resources": cr["spec"].get("resources", {}),
            }
            for cr in self.runtime.current_agents(tenant, name)
        ]

    # ------------------------------------------------------------------
    # fleet plane: observe / scale / drain (docs/FLEET.md)
    # ------------------------------------------------------------------

    def serving_statefulsets(
        self, tenant: str, name: str
    ) -> list[dict[str, Any]]:
        """The application's *scalable* StatefulSets: single-host agents
        whose replicas are data-parallel pods. Multi-host ICI slices are
        excluded — their STS replica count is the slice's HOST count
        (one JAX process group), and "scaling" it would tear the
        collective topology, not add serving capacity; slice fan-out is
        the factory's per-logical-replica STS split instead."""
        from langstream_tpu.k8s.cluster_runtime import tenant_namespace

        namespace = tenant_namespace(tenant)
        out = []
        for sts in self.api.list(
            "StatefulSet", namespace,
            label_selector={"langstream-application": name},
        ):
            template = (
                (sts["spec"].get("template") or {}).get("spec") or {}
            )
            env = {
                e.get("name"): e.get("value")
                for c in template.get("containers", [])
                for e in c.get("env", [])
            }
            if int(env.get("LS_SLICE_HOSTS") or 1) > 1:
                continue
            out.append(sts)
        return out

    def fleet_observe(
        self, tenant: str, name: str, sts_name: str
    ) -> list[dict[str, Any]]:
        """One :class:`ReplicaObservation` dict per pod of ``sts_name``,
        folded from the pods' ``/flight/summary`` fan-in (queue depths,
        occupancy, KV pressure, health/drain posture, SLO alerts).
        Timed-out pods surface as ``unreachable`` members — the
        autoscaler treats a missing replica as a reason NOT to scale
        down, never as absent capacity."""
        from langstream_tpu.controlplane.autoscaler import (
            observation_from_summary,
        )

        prefix = f"{sts_name}-"
        observations = []
        for pod, chunk in self._pod_json_fanin(tenant, name, "/flight/summary"):
            # exact-STS match: the tail must be the pod ORDINAL, or a
            # sibling STS whose name extends this one's ("chat-ai" vs
            # "chat-ai-extra") would leak its pods into this fleet —
            # the same dash-prefix leak shape pod_logs fixed with label
            # selectors
            if not pod.startswith(prefix) or not pod[len(prefix):].isdigit():
                continue
            observations.append(observation_from_summary(pod, chunk).to_dict())
        return observations

    def scale_statefulset(
        self, tenant: str, name: str, sts_name: str, replicas: int
    ) -> None:
        """Patch the StatefulSet's replica count, stamping the autoscale
        annotation so the operator's level-triggered reconcile preserves
        the live value instead of resetting it to the CR's parallelism
        (``AgentController._preserve_autoscaled_replicas``)."""
        from langstream_tpu.controlplane.autoscaler import AUTOSCALE_ANNOTATION
        from langstream_tpu.k8s.cluster_runtime import tenant_namespace

        namespace = tenant_namespace(tenant)
        sts = self.api.get("StatefulSet", namespace, sts_name)
        if sts is None:
            raise KeyError(f"StatefulSet {sts_name!r} not found in {namespace}")
        sts["spec"]["replicas"] = int(replicas)
        sts.setdefault("metadata", {}).setdefault("annotations", {})[
            AUTOSCALE_ANNOTATION
        ] = "true"
        self.api.apply(sts)
        self.append_log(
            tenant, name, f"autoscaler: {sts_name} replicas -> {replicas}"
        )

    def drain_pod(
        self, tenant: str, name: str, pod: str, grace_s: float = 30.0
    ) -> dict[str, Any] | None:
        """Hit one pod's ``/drain`` endpoint and block until it settles
        (the endpoint answers only after the engines requeued their work
        or the grace budget expired). ``None`` when the pod is already
        unreachable — for the scale-down path that is equivalent to a
        drained pod: there is nothing left to lose on it. Synchronous by
        design (the autoscaler runs backend calls in a worker thread)."""
        import json as _json
        import urllib.error
        import urllib.request

        base = self._pod_addresses(tenant, name).get(pod)
        if base is None:
            return None
        url = f"{base}/drain?grace-s={float(grace_s):g}"
        try:
            with urllib.request.urlopen(url, timeout=grace_s + 10) as resp:
                return _json.loads(resp.read())
        except (urllib.error.URLError, OSError, ValueError) as e:
            log.warning("drain of pod %s failed (%s); treating as gone", pod, e)
            return None

    def autoscaler_backend(self, tenant: str, name: str, spec) -> Any:
        """A :class:`FleetAutoscaler` backend for the app's serving
        StatefulSet (``spec.agent`` disambiguates when the app has
        several scalable agents). STS resolution is LAZY — at deploy
        time the operator has not reconciled the Agent CRs into
        StatefulSets yet, so the backend re-resolves per observation
        until one exists (an unresolved fleet observes as empty, which
        the autoscaler treats as "nothing to decide")."""
        return StatefulSetFleetBackend(self, tenant, name, spec)

    async def close(self) -> None:
        pass


class StatefulSetFleetBackend:
    """The duck-typed backend a :class:`FleetAutoscaler` drives against a
    live cluster: observe = pod ``/flight/summary`` fan-in, scale =
    StatefulSet replica patch, drain = pod ``/drain``. All methods are
    synchronous (pod HTTP + API-server round-trips); the autoscaler runs
    them in a worker thread so the control plane's event loop — and the
    wait-free decide() — never block on a slow pod."""

    def __init__(
        self,
        runtime: KubernetesComputeRuntime,
        tenant: str,
        name: str,
        spec: Any = None,
    ):
        self.runtime = runtime
        self.tenant = tenant
        self.name = name
        self.spec = spec
        self._sts_name: str | None = None

    def resolve(self) -> str | None:
        """The target StatefulSet's name, re-resolved until the operator
        has materialized it (cached afterwards — STS names are stable
        for an app's lifetime)."""
        if self._sts_name is not None:
            return self._sts_name
        from langstream_tpu.k8s.resources import AgentResourcesFactory

        candidates = self.runtime.serving_statefulsets(self.tenant, self.name)
        if self.spec is not None and getattr(self.spec, "agent", None):
            wanted = AgentResourcesFactory.agent_resource_name(
                self.name, self.spec.agent
            )
            pool = getattr(self.spec, "pool", None)
            wanted_names = {wanted}
            if pool:
                wanted_names.add(f"{wanted}-{pool}")
            candidates = [
                s
                for s in candidates
                if s["metadata"]["name"] in wanted_names
            ]
        if self.spec is not None and getattr(self.spec, "pool", None):
            # disaggregated split (docs/DISAGG.md): each pool's policy
            # scales ITS StatefulSet — the factory names them
            # `<agent-sts>-<role>`
            suffix = f"-{self.spec.pool}"
            pooled = [
                s
                for s in candidates
                if s["metadata"]["name"].endswith(suffix)
            ]
            if candidates and not pooled:
                # StatefulSets exist but none carries this pool's
                # suffix: the app declared a pools: autoscale policy
                # without the agent-level pool-roles split — a
                # misconfiguration, not a not-yet-materialized STS, so
                # say so instead of lazily resolving forever
                log.warning(
                    "application %s/%s declares a pools.%s autoscale "
                    "policy but no '-%s' StatefulSet exists (agents: "
                    "%s) — declare pool-roles on the serving agent so "
                    "the fleet actually splits (docs/DISAGG.md)",
                    self.tenant, self.name, self.spec.pool,
                    self.spec.pool,
                    sorted(s["metadata"]["name"] for s in candidates),
                )
            candidates = pooled
        if not candidates:
            return None
        if len(candidates) > 1:
            log.warning(
                "application %s/%s has %d scalable StatefulSets and no "
                "autoscale.agent — scaling %s",
                self.tenant, self.name, len(candidates),
                sorted(s["metadata"]["name"] for s in candidates)[0],
            )
        self._sts_name = sorted(
            s["metadata"]["name"] for s in candidates
        )[0]
        return self._sts_name

    def observe(self) -> list[dict[str, Any]]:
        sts_name = self.resolve()
        if sts_name is None:
            return []
        return self.runtime.fleet_observe(self.tenant, self.name, sts_name)

    def set_replicas(self, replicas: int) -> None:
        sts_name = self.resolve()
        if sts_name is None:
            raise KeyError(
                f"no scalable StatefulSet for {self.tenant}/{self.name}"
            )
        self.runtime.scale_statefulset(
            self.tenant, self.name, sts_name, replicas
        )

    def drain(self, replica: str, grace_s: float) -> dict[str, Any] | None:
        return self.runtime.drain_pod(
            self.tenant, self.name, replica, grace_s
        )
