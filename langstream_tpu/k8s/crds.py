"""Custom-resource models + CRD manifests.

Parity: ``langstream-k8s-deployer-api`` CR POJOs —
``ApplicationCustomResource``/``ApplicationSpec`` (serialized app +
codeArchiveId) and ``AgentCustomResource``/``AgentSpec``
(``.../crds/agents/AgentSpec.java:33-57``: agentId, applicationId,
``agentConfigSecretRef`` + checksum, resources{parallelism, size}, disks).

CRs are plain dicts on the wire (what the API server stores); the dataclasses
here are the typed view both the deployer and the operator share.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Any

GROUP = "langstream.tpu"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"


@dataclass
class ApplicationSpec:
    tenant: str
    image: str = ""
    application: str = ""  # serialized application (JSON)
    code_archive_id: str | None = None
    options: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "tenant": self.tenant,
            "image": self.image,
            "application": self.application,
            "codeArchiveId": self.code_archive_id,
            "options": self.options,
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "ApplicationSpec":
        return cls(
            tenant=data.get("tenant", ""),
            image=data.get("image", ""),
            application=data.get("application", ""),
            code_archive_id=data.get("codeArchiveId"),
            options=data.get("options") or {},
        )


@dataclass
class DiskSpecCR:
    enabled: bool = False
    size: str = "128M"
    type: str = "default"

    def to_dict(self) -> dict[str, Any]:
        return {"enabled": self.enabled, "size": self.size, "type": self.type}


@dataclass
class AgentResourcesCR:
    parallelism: int = 1
    size: int = 1
    # TPU extension: ICI mesh shape one logical replica needs (chips =
    # product of axis sizes); absent → CPU-only agent pod.
    device_mesh: dict[str, int] | None = None

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"parallelism": self.parallelism, "size": self.size}
        if self.device_mesh:
            out["deviceMesh"] = self.device_mesh
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any] | None) -> "AgentResourcesCR":
        data = data or {}
        return cls(
            parallelism=int(data.get("parallelism", 1)),
            size=int(data.get("size", 1)),
            device_mesh=data.get("deviceMesh"),
        )


@dataclass
class AgentSpec:
    tenant: str
    application_id: str
    agent_id: str
    image: str = ""
    agent_config_secret_ref: str = ""
    agent_config_secret_ref_checksum: str = ""
    resources: AgentResourcesCR = field(default_factory=AgentResourcesCR)
    disk: DiskSpecCR | None = None
    options: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        out = {
            "tenant": self.tenant,
            "applicationId": self.application_id,
            "agentId": self.agent_id,
            "image": self.image,
            "agentConfigSecretRef": self.agent_config_secret_ref,
            "agentConfigSecretRefChecksum": self.agent_config_secret_ref_checksum,
            "resources": self.resources.to_dict(),
            "options": self.options,
        }
        if self.disk is not None:
            out["disk"] = self.disk.to_dict()
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "AgentSpec":
        disk = data.get("disk")
        return cls(
            tenant=data.get("tenant", ""),
            application_id=data.get("applicationId", ""),
            agent_id=data.get("agentId", ""),
            image=data.get("image", ""),
            agent_config_secret_ref=data.get("agentConfigSecretRef", ""),
            agent_config_secret_ref_checksum=data.get(
                "agentConfigSecretRefChecksum", ""
            ),
            resources=AgentResourcesCR.from_dict(data.get("resources")),
            disk=DiskSpecCR(**disk) if disk else None,
            options=data.get("options") or {},
        )


def _meta(name: str, namespace: str, labels: dict[str, str] | None = None) -> dict:
    meta: dict[str, Any] = {"name": name, "namespace": namespace}
    if labels:
        meta["labels"] = labels
    return meta


@dataclass
class ApplicationCustomResource:
    name: str
    namespace: str
    spec: ApplicationSpec
    status: dict[str, Any] = field(default_factory=dict)

    PLURAL = "applications"
    KIND = "Application"

    def to_dict(self) -> dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": _meta(self.name, self.namespace),
            "spec": self.spec.to_dict(),
            "status": self.status,
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "ApplicationCustomResource":
        return cls(
            name=data["metadata"]["name"],
            namespace=data["metadata"].get("namespace", "default"),
            spec=ApplicationSpec.from_dict(data.get("spec") or {}),
            status=data.get("status") or {},
        )


@dataclass
class AgentCustomResource:
    name: str
    namespace: str
    spec: AgentSpec
    status: dict[str, Any] = field(default_factory=dict)

    PLURAL = "agents"
    KIND = "Agent"

    def to_dict(self) -> dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": _meta(
                self.name,
                self.namespace,
                labels={
                    "app": "langstream-tpu-runtime",
                    "langstream-application": self.spec.application_id,
                    "langstream-agent": self.spec.agent_id,
                },
            ),
            "spec": self.spec.to_dict(),
            "status": self.status,
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "AgentCustomResource":
        return cls(
            name=data["metadata"]["name"],
            namespace=data["metadata"].get("namespace", "default"),
            spec=AgentSpec.from_dict(data.get("spec") or {}),
            status=data.get("status") or {},
        )


def config_checksum(config: dict[str, Any]) -> str:
    """Checksum of an agent's pod configuration; a changed checksum is what
    forces the operator to roll the StatefulSet (parity: the reference's
    ``agentConfigSecretRefChecksum``)."""
    canon = json.dumps(config, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def crd_manifests() -> list[dict[str, Any]]:
    """CRD definitions (parity: ``helm/crds/*.yml``)."""

    def crd(kind: str, plural: str, short: str) -> dict[str, Any]:
        return {
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "CustomResourceDefinition",
            "metadata": {"name": f"{plural}.{GROUP}"},
            "spec": {
                "group": GROUP,
                "names": {
                    "kind": kind,
                    "plural": plural,
                    "singular": kind.lower(),
                    "shortNames": [short],
                },
                "scope": "Namespaced",
                "versions": [
                    {
                        "name": VERSION,
                        "served": True,
                        "storage": True,
                        "subresources": {"status": {}},
                        "schema": {
                            "openAPIV3Schema": {
                                "type": "object",
                                "properties": {
                                    "spec": {
                                        "type": "object",
                                        "x-kubernetes-preserve-unknown-fields": True,
                                    },
                                    "status": {
                                        "type": "object",
                                        "x-kubernetes-preserve-unknown-fields": True,
                                    },
                                },
                            }
                        },
                    }
                ],
            },
        }

    return [
        crd(ApplicationCustomResource.KIND, ApplicationCustomResource.PLURAL, "lsapp"),
        crd(AgentCustomResource.KIND, AgentCustomResource.PLURAL, "lsagent"),
    ]
