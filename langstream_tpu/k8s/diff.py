"""Spec diffing + tenant resource limits.

Parity: ``SpecDiffer`` (``langstream-k8s-deployer-core/.../util/
SpecDiffer.java`` — decides whether a spec change requires a pod restart) and
``ApplicationResourceLimitsChecker``
(``.../limits/ApplicationResourceLimitsChecker.java`` — per-tenant unit
quotas; a unit is ``parallelism × size``).
"""

from __future__ import annotations

from typing import Any


def specs_equal(a: Any, b: Any) -> bool:
    """Structural equality with None ≡ {} ≡ absent (the reference treats
    missing maps and empty maps as the same spec)."""
    if a is None:
        a = {}
    if b is None:
        b = {}
    if isinstance(a, dict) and isinstance(b, dict):
        keys = set(a) | set(b)
        return all(specs_equal(a.get(k), b.get(k)) for k in keys)
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(specs_equal(x, y) for x, y in zip(a, b))
    return a == b


def diff_paths(a: Any, b: Any, prefix: str = "") -> list[str]:
    """Dotted paths where two specs differ (for update-validation messages)."""
    if a is None:
        a = {}
    if b is None:
        b = {}
    if isinstance(a, dict) and isinstance(b, dict):
        out: list[str] = []
        for k in sorted(set(a) | set(b)):
            out.extend(diff_paths(a.get(k), b.get(k), f"{prefix}{k}."))
        return out
    if isinstance(a, list) and isinstance(b, list) and len(a) == len(b):
        out = []
        for i, (x, y) in enumerate(zip(a, b)):
            out.extend(diff_paths(x, y, f"{prefix}{i}."))
        return out
    return [] if a == b else [prefix.rstrip(".") or "<root>"]


def agent_needs_restart(old_spec: dict[str, Any], new_spec: dict[str, Any]) -> bool:
    """An Agent CR change restarts pods only when pod-visible fields change
    (config checksum, image, resources, disk) — status/metadata churn
    doesn't."""
    relevant = (
        "agentConfigSecretRefChecksum",
        "image",
        "resources",
        "disk",
        "agentConfigSecretRef",
    )
    return any(
        not specs_equal(old_spec.get(k), new_spec.get(k)) for k in relevant
    )


class ResourceLimitsChecker:
    """Per-tenant unit quota: Σ over agents of parallelism × size ≤ max."""

    def __init__(self, max_units: int | None):
        self.max_units = max_units

    @staticmethod
    def units(agents: list[dict[str, Any]]) -> int:
        total = 0
        for spec in agents:
            resources = spec.get("resources") or {}
            total += int(resources.get("parallelism", 1)) * int(
                resources.get("size", 1)
            )
        return total

    def check(
        self,
        existing_agents_by_app: dict[str, list[dict[str, Any]]],
        new_app_id: str,
        new_agents: list[dict[str, Any]],
    ) -> None:
        """Raises ValueError when deploying/updating ``new_app_id`` would
        push the tenant over its quota (the app's own previous usage is
        released first)."""
        if self.max_units is None:
            return
        used = sum(
            self.units(agents)
            for app_id, agents in existing_agents_by_app.items()
            if app_id != new_app_id
        )
        wanted = self.units(new_agents)
        if used + wanted > self.max_units:
            raise ValueError(
                f"tenant quota exceeded: {used} units in use, application "
                f"{new_app_id!r} needs {wanted}, limit {self.max_units}"
            )
