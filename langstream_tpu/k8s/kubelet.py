"""ProcessKubelet: run StatefulSet and Job pods as local OS processes.

The mini-cluster lane (parity: ``mini-langstream``'s minikube — the
reference stands its whole control plane up in a local cluster and runs
REAL pods; no container runtime exists in this image, so pods here are
subprocesses). Combined with the in-memory/HTTP kube API server, the
operator, the control plane in k8s mode, and the native tsbroker, this
executes the ENTIRE production deploy path — Application CR → setup Job →
deployer Job → Agent CRs → StatefulSets → running agent processes — with
the same manifests and the same pod entrypoint
(``python -m langstream_tpu.runtime.pod``) the real cluster runs.

kubelet-isms implemented:
- volumes: ``secret`` (keys materialized as files), ``emptyDir``,
  ``persistentVolumeClaim``/``volumeClaimTemplates`` (a per-claim dir under
  the state root — data survives pod restarts, like a PVC);
- mountPaths: pods are processes, so absolute container paths
  (``/app-config``) are rewritten to per-pod dirs in the command argv;
- env: literal values and the ``fieldRef: metadata.name`` downward API;
- initContainers run to completion before the main container starts;
- Jobs: run once, then the Job's ``status.succeeded/failed`` is patched so
  the operator's two-phase deploy advances;
- StatefulSet scale-up/down/update: pods are (re)started when the template
  changes (hash-tracked) and killed on scale-down/delete; readyReplicas is
  patched back into status so Agent CR statuses progress to DEPLOYED.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from langstream_tpu.k8s.client import KubeApi

log = logging.getLogger("langstream_tpu.kubelet")


@dataclass
class _Pod:
    name: str
    namespace: str
    kind: str               # "StatefulSet" | "Job"
    owner: str              # owning object name
    template_hash: str
    proc: subprocess.Popen | None = None
    root: Path | None = None
    log_path: Path | None = None
    init_ok: bool = True
    failed: bool = False
    reported: bool = False  # job completion already patched
    env: dict[str, str] = field(default_factory=dict)


def _hash_template(template: dict[str, Any]) -> str:
    return hashlib.sha256(
        json.dumps(template, sort_keys=True).encode()
    ).hexdigest()[:16]


class ProcessKubelet:
    """Reconciles StatefulSets + Jobs from a KubeApi into subprocesses."""

    def __init__(
        self,
        api: KubeApi,
        root: Path | str,
        env_extra: dict[str, str] | None = None,
        python: str | None = None,
    ):
        self.api = api
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        # handed to every pod: LS_KUBE_API_URL (so in_cluster() reaches the
        # mini API server), broker addresses, JAX platform pins, ...
        self.env_extra = dict(env_extra or {})
        self.python = python or sys.executable
        self.pods: dict[tuple[str, str], _Pod] = {}  # (ns, pod name)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- pod mechanics -----------------------------------------------------

    def _materialize_volumes(
        self, pod: _Pod, pod_spec: dict[str, Any], sts_claims: list[dict]
    ) -> dict[str, Path]:
        """volume name → host dir. Secret keys become files; PVCs map to
        stable per-claim dirs so state survives restarts."""
        mounts: dict[str, Path] = {}
        for vol in pod_spec.get("volumes", []):
            name = vol["name"]
            if "secret" in vol:
                target = pod.root / "volumes" / name
                target.mkdir(parents=True, exist_ok=True)
                secret = self.api.get(
                    "Secret", pod.namespace, vol["secret"]["secretName"]
                )
                if secret is None:
                    raise FileNotFoundError(
                        f"secret {vol['secret']['secretName']} not found "
                        f"for pod {pod.name}"
                    )
                for key, b64 in (secret.get("data") or {}).items():
                    (target / key).write_bytes(base64.b64decode(b64))
                mounts[name] = target
            elif "emptyDir" in vol:
                target = pod.root / "volumes" / name
                target.mkdir(parents=True, exist_ok=True)
                mounts[name] = target
            elif "persistentVolumeClaim" in vol:
                claim = vol["persistentVolumeClaim"]["claimName"]
                target = self.root / "pvc" / pod.namespace / claim
                target.mkdir(parents=True, exist_ok=True)
                mounts[name] = target
            else:  # configMap etc. — none emitted by our factories yet
                target = pod.root / "volumes" / name
                target.mkdir(parents=True, exist_ok=True)
                mounts[name] = target
        for claim in sts_claims:
            # volumeClaimTemplates: claim name <template>-<pod>
            name = claim["metadata"]["name"]
            target = self.root / "pvc" / pod.namespace / f"{name}-{pod.name}"
            target.mkdir(parents=True, exist_ok=True)
            mounts[name] = target
        return mounts

    def _container_cmd(
        self, container: dict[str, Any], mounts: dict[str, Path]
    ) -> list[str]:
        """Rewrite absolute container mount paths in argv to host dirs, and
        run the image's python entrypoint with THIS interpreter."""
        path_map = {
            vm["mountPath"]: str(mounts[vm["name"]])
            for vm in container.get("volumeMounts", [])
            if vm["name"] in mounts
        }
        cmd = []
        for arg in container.get("command", []) + container.get("args", []):
            for cpath, hpath in path_map.items():
                if arg == cpath or arg.startswith(cpath + "/"):
                    arg = hpath + arg[len(cpath):]
                    break
            cmd.append(arg)
        if cmd and cmd[0] == "python":
            cmd[0] = self.python
        return cmd

    def _container_env(
        self, pod: _Pod, container: dict[str, Any]
    ) -> dict[str, str]:
        env = dict(os.environ)
        env.update(self.env_extra)
        for e in container.get("env", []):
            if "value" in e:
                env[e["name"]] = str(e["value"])
            elif (
                e.get("valueFrom", {})
                .get("fieldRef", {})
                .get("fieldPath")
                == "metadata.name"
            ):
                env[e["name"]] = pod.name
        return env

    def _start_pod(
        self,
        pod: _Pod,
        template: dict[str, Any],
        sts_claims: list[dict] | None = None,
    ) -> None:
        pod.root = self.root / "pods" / pod.namespace / pod.name
        pod.root.mkdir(parents=True, exist_ok=True)
        pod.log_path = pod.root / "pod.log"
        pod_spec = template["spec"]
        try:
            mounts = self._materialize_volumes(
                pod, pod_spec, sts_claims or []
            )
        except FileNotFoundError as e:
            log.warning("pod %s: %s (will retry)", pod.name, e)
            pod.failed = True
            return
        log_f = open(pod.log_path, "ab")
        for init in pod_spec.get("initContainers", []):
            cmd = self._container_cmd(init, mounts)
            rc = subprocess.call(
                cmd, env=self._container_env(pod, init),
                stdout=log_f, stderr=subprocess.STDOUT,
            )
            if rc != 0:
                log.warning(
                    "pod %s init container %s failed rc=%d (log: %s)",
                    pod.name, init.get("name"), rc, pod.log_path,
                )
                pod.failed = True
                log_f.close()
                return
        containers = pod_spec.get("containers", [])
        main = containers[0]
        cmd = self._container_cmd(main, mounts)
        pod.env = self._container_env(pod, main)
        pod.proc = subprocess.Popen(
            cmd, env=pod.env, stdout=log_f, stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        log_f.close()
        log.info("pod %s/%s started (pid %d): %s",
                 pod.namespace, pod.name, pod.proc.pid, " ".join(cmd[-3:]))

    def _kill_pod(self, pod: _Pod) -> None:
        if pod.proc is not None and pod.proc.poll() is None:
            try:
                os.killpg(pod.proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pod.proc.terminate()
            try:
                pod.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(pod.proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pod.proc.kill()
                pod.proc.wait()
        pod.proc = None

    # -- reconcile ---------------------------------------------------------

    def _namespaces(self) -> list[str]:
        return [
            ns["metadata"]["name"] for ns in self.api.list("Namespace", None)
        ]

    def reconcile_once(self) -> None:
        desired: set[tuple[str, str]] = set()
        for ns in self._namespaces():
            for sts in self.api.list("StatefulSet", ns):
                desired |= self._sync_statefulset(ns, sts)
            for job in self.api.list("Job", ns):
                desired |= self._sync_job(ns, job)
        # pods whose owner is gone
        for key, pod in list(self.pods.items()):
            if key not in desired:
                log.info("pod %s/%s: owner gone, stopping", *key)
                self._kill_pod(pod)
                del self.pods[key]

    def _sync_statefulset(
        self, ns: str, sts: dict[str, Any]
    ) -> set[tuple[str, str]]:
        name = sts["metadata"]["name"]
        replicas = int(sts["spec"].get("replicas", 1))
        template = sts["spec"]["template"]
        claims = sts["spec"].get("volumeClaimTemplates", [])
        thash = _hash_template(template)
        keys: set[tuple[str, str]] = set()
        ready = 0
        for i in range(replicas):
            pod_name = f"{name}-{i}"
            key = (ns, pod_name)
            keys.add(key)
            pod = self.pods.get(key)
            if pod is not None and pod.template_hash != thash:
                self._kill_pod(pod)
                pod = None
            if pod is not None and pod.failed:
                # secret not yet present / init failure: retry from scratch
                self._kill_pod(pod)
                pod = None
            if pod is None:
                pod = _Pod(
                    name=pod_name, namespace=ns, kind="StatefulSet",
                    owner=name, template_hash=thash,
                )
                self.pods[key] = pod
                self._start_pod(pod, template, claims)
            elif pod.proc is not None and pod.proc.poll() is not None:
                log.warning(
                    "pod %s/%s exited rc=%s; restarting",
                    ns, pod_name, pod.proc.returncode,
                )
                self._start_pod(pod, template, claims)
            if pod.proc is not None and pod.proc.poll() is None:
                ready += 1
        status = sts.get("status") or {}
        if (
            status.get("readyReplicas") != ready
            or status.get("replicas") != replicas
        ):
            sts["status"] = {"readyReplicas": ready, "replicas": replicas}
            try:
                self.api.update_status(sts)
            except Exception as e:
                log.debug("sts status update conflict (next pass re-reads): %s", e)
        return keys

    def _sync_job(self, ns: str, job: dict[str, Any]) -> set[tuple[str, str]]:
        name = job["metadata"]["name"]
        key = (ns, name)
        status = job.get("status") or {}
        if status.get("succeeded") or status.get("failed"):
            return {key} if key in self.pods else set()
        template = job["spec"]["template"]
        thash = _hash_template(template)
        pod = self.pods.get(key)
        if pod is None:
            pod = _Pod(
                name=name, namespace=ns, kind="Job", owner=name,
                template_hash=thash,
            )
            self.pods[key] = pod
            self._start_pod(pod, template)
            if pod.failed:
                # config secret may land a moment after the Job: retry next
                # pass rather than marking the Job failed
                del self.pods[key]
                return set()
        if pod.proc is not None and pod.proc.poll() is not None and not pod.reported:
            rc = pod.proc.returncode
            job["status"] = (
                {"succeeded": 1} if rc == 0 else {"failed": 1}
            )
            if rc != 0:
                log.warning(
                    "job %s/%s failed rc=%d (log: %s)",
                    ns, name, rc, pod.log_path,
                )
            try:
                self.api.update_status(job)
                pod.reported = True
            except Exception as e:
                log.debug("job status update conflict (next pass retries): %s", e)
        return {key}

    # -- lifecycle ---------------------------------------------------------

    def start(self, interval: float = 0.5) -> "ProcessKubelet":
        def _run() -> None:
            while not self._stop.is_set():
                try:
                    self.reconcile_once()
                except Exception:
                    log.exception("kubelet reconcile pass failed")
                self._stop.wait(interval)

        self._thread = threading.Thread(
            target=_run, name="process-kubelet", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(15)
        for pod in self.pods.values():
            self._kill_pod(pod)
        self.pods.clear()
