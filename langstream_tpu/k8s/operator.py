"""The operator: reconcilers for Application and Agent CRs.

Parity: ``langstream-k8s-deployer-operator`` —
``AppController.reconcile`` (Application CR → setup Job, then deployer Job;
``controllers/apps/AppController.java:54,314``) and
``AgentController.reconcile`` (Agent CR → StatefulSet(s) + headless Service,
status DEPLOYING/DEPLOYED from STS readiness;
``controllers/agents/AgentController.java:49-92``), with infinite retry
(``InfiniteRetry.java``) expressed as a poll loop that never gives up on a
failing resource.

The reconcilers are pure functions of (CR, cluster state) → (writes, status),
so they run identically against :class:`InMemoryKubeApi` in tests and
:class:`HttpKubeApi` in a cluster.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import time
from typing import Any

from langstream_tpu.k8s.client import KubeApi
from langstream_tpu.k8s.crds import (
    AgentCustomResource,
    ApplicationCustomResource,
    config_checksum,
)
from langstream_tpu.k8s.diff import specs_equal
from langstream_tpu.k8s.resources import AgentResourcesFactory, AppResourcesFactory

log = logging.getLogger(__name__)

# Application/Agent lifecycle statuses (parity: ApplicationLifecycleStatus)
DEPLOYING = "DEPLOYING"
DEPLOYED = "DEPLOYED"
ERROR_DEPLOYING = "ERROR_DEPLOYING"
DELETING = "DELETING"


def apply_if_changed(api: KubeApi, obj: dict[str, Any]) -> dict[str, Any]:
    """Level-triggered writes without churn: skip the PUT when the desired
    spec/data/labels/ownerReferences already match (every tick would
    otherwise rewrite every object, hammering the API server and bumping
    resourceVersions). ownerReferences participate so dependents created
    before owner-stamping existed still get their refs on the next tick —
    without them, deleting the owning CR would orphan them forever."""
    meta = obj.get("metadata") or {}
    existing = api.get(obj["kind"], meta.get("namespace"), meta["name"])
    existing_meta = (existing or {}).get("metadata") or {}
    if existing is not None and all(
        specs_equal(obj.get(k), existing.get(k)) for k in ("spec", "data")
    ) and specs_equal(meta.get("labels"), existing_meta.get("labels")) and specs_equal(
        meta.get("ownerReferences"), existing_meta.get("ownerReferences")
    ):
        return existing
    return api.apply(obj)


class AgentController:
    """Agent CR → StatefulSet(s) + headless Service; status from readiness."""

    def __init__(self, api: KubeApi, accelerator: str = "v5e"):
        self.api = api
        self.accelerator = accelerator

    @staticmethod
    def _own(obj: dict[str, Any], cr_dict: dict[str, Any]) -> dict[str, Any]:
        """Stamp the Agent CR as controller-owner so deleting the CR
        cascades to its dependents via server-side garbage collection
        (parity: fabric8 dependents in AgentController.java — dependents
        carry owner references, the API server GC does the deletion)."""
        meta = cr_dict.get("metadata") or {}
        if meta.get("uid"):
            obj.setdefault("metadata", {})["ownerReferences"] = [{
                "apiVersion": "langstream.tpu/v1alpha1",
                "kind": "Agent",
                "name": meta["name"],
                "uid": meta["uid"],
                "controller": True,
                "blockOwnerDeletion": True,
            }]
        return obj

    def _preserve_autoscaled_replicas(self, sts: dict[str, Any]) -> None:
        """The fleet autoscaler owns the replica count of StatefulSets it
        has stamped (``langstream.tpu/autoscale``): the level-triggered
        reconcile must carry the LIVE count (and the stamp) into the
        desired spec, or every tick would fight the autoscaler back to
        the CR's parallelism — exactly the churn HPA-managed Deployments
        avoid by omitting ``replicas``."""
        from langstream_tpu.controlplane.autoscaler import AUTOSCALE_ANNOTATION

        meta = sts["metadata"]
        existing = self.api.get("StatefulSet", meta["namespace"], meta["name"])
        if existing is None:
            return
        annotations = (existing.get("metadata") or {}).get("annotations") or {}
        if annotations.get(AUTOSCALE_ANNOTATION) != "true":
            return
        live = (existing.get("spec") or {}).get("replicas")
        if live is not None:
            sts["spec"]["replicas"] = int(live)
        meta.setdefault("annotations", {})[AUTOSCALE_ANNOTATION] = "true"

    def reconcile(self, cr_dict: dict[str, Any]) -> str:
        cr = AgentCustomResource.from_dict(cr_dict)
        service = self._own(
            AgentResourcesFactory.generate_headless_service(cr), cr_dict
        )
        apply_if_changed(self.api, service)
        statefulsets = [
            self._own(sts, cr_dict)
            for sts in AgentResourcesFactory.generate_statefulsets(
                cr, accelerator=self.accelerator
            )
        ]
        for sts in statefulsets:
            self._preserve_autoscaled_replicas(sts)
        # voluntary-eviction protection: one PDB per STS (maxUnavailable 1)
        # so node drains take serving pods one at a time through the same
        # preStop /drain path the autoscaler's scale-down uses
        pdbs = [
            self._own(pdb, cr_dict)
            for pdb in AgentResourcesFactory.generate_pod_disruption_budgets(
                cr, statefulsets
            )
        ]
        # prune StatefulSets (and their PDBs) from a previous shape (e.g.
        # parallelism shrank or the agent moved between single- and
        # multi-host)
        wanted = {sts["metadata"]["name"] for sts in statefulsets}
        selector = {
            "langstream-application": cr.spec.application_id,
            "langstream-agent": cr.spec.agent_id,
        }
        for kind in ("StatefulSet", "PodDisruptionBudget"):
            for obj in self.api.list(kind, cr.namespace, label_selector=selector):
                if obj["metadata"]["name"] not in wanted:
                    self.api.delete(kind, cr.namespace, obj["metadata"]["name"])
        for pdb in pdbs:
            apply_if_changed(self.api, pdb)
        ready = True
        for sts in statefulsets:
            applied = apply_if_changed(self.api, sts)
            status = (applied or {}).get("status") or {}
            if status.get("readyReplicas", 0) < sts["spec"]["replicas"]:
                ready = False
        phase = DEPLOYED if ready else DEPLOYING
        if (cr.status or {}).get("status") != phase:
            self.api.update_status(
                {**cr_dict, "status": {**cr.status, "status": phase}}
            )
        return phase

    def cleanup(self, cr_dict: dict[str, Any]) -> None:
        cr = AgentCustomResource.from_dict(cr_dict)
        selector = {
            "langstream-application": cr.spec.application_id,
            "langstream-agent": cr.spec.agent_id,
        }
        for kind in ("StatefulSet", "PodDisruptionBudget"):
            for obj in self.api.list(kind, cr.namespace, label_selector=selector):
                self.api.delete(kind, cr.namespace, obj["metadata"]["name"])
        name = AgentResourcesFactory.agent_resource_name(
            cr.spec.application_id, cr.spec.agent_id
        )
        self.api.delete("Service", cr.namespace, name)


class AppController:
    """Application CR → setup Job → deployer Job (two-phase deploy)."""

    def __init__(self, api: KubeApi):
        self.api = api

    def _ensure_app_config_secret(
        self, cr: ApplicationCustomResource
    ) -> tuple[str, str]:
        """Materialize the config document the setup/deployer Jobs mount:
        the parsed files + instance from the Application CR, the secrets
        YAML from the companion ``<app>-secrets`` Secret, and code-storage
        coordinates (what :func:`runtime.pod.run_setup`/``run_deployer``
        read). Returns (secret name, config checksum) — the checksum keys
        the Jobs' identity so an updated app re-runs them."""
        name = f"{cr.name}-app-config"
        payload = json.loads(cr.spec.application or "{}")
        secrets_yaml = None
        secrets_obj = self.api.get("Secret", cr.namespace, f"{cr.name}-secrets")
        if secrets_obj is not None:
            raw = (secrets_obj.get("data") or {}).get("secrets", "")
            secrets_yaml = base64.b64decode(raw).decode() if raw else None
        config = {
            "applicationId": cr.name,
            "tenant": cr.spec.tenant,
            "image": cr.spec.image,
            "files": payload.get("files") or {},
            "instance": payload.get("instance"),
            "secrets": secrets_yaml,
            "codeArchiveId": cr.spec.code_archive_id,
            "codeStorage": (cr.spec.options or {}).get("codeStorage") or {},
        }
        apply_if_changed(
            self.api,
            {
                "apiVersion": "v1",
                "kind": "Secret",
                "metadata": {
                    "name": name,
                    "namespace": cr.namespace,
                    "labels": {"langstream-application": cr.name},
                },
                "data": {
                    "config": base64.b64encode(
                        json.dumps(config).encode()
                    ).decode()
                },
            },
        )
        return name, config_checksum(config)

    def _prune_stale_jobs(
        self, cr: ApplicationCustomResource, keep: set[str]
    ) -> None:
        for job in self.api.list(
            "Job", cr.namespace, label_selector={"langstream-application": cr.name}
        ):
            if job["metadata"]["name"] not in keep:
                self.api.delete("Job", cr.namespace, job["metadata"]["name"])

    def reconcile(self, cr_dict: dict[str, Any]) -> str:
        cr = ApplicationCustomResource.from_dict(cr_dict)
        image = cr.spec.image
        config_secret, checksum = self._ensure_app_config_secret(cr)
        suffix = f"-{checksum[:8]}"
        setup_job = AppResourcesFactory.generate_setup_job(
            cr.spec.tenant, cr.name, cr.namespace, image, config_secret,
            name_suffix=suffix,
        )
        deployer_job = AppResourcesFactory.generate_deployer_job(
            cr.spec.tenant, cr.name, cr.namespace, image, config_secret,
            name_suffix=suffix,
        )
        # an updated app produces a new checksum → fresh jobs; older
        # generations' jobs are pruned
        self._prune_stale_jobs(
            cr,
            keep={
                setup_job["metadata"]["name"],
                deployer_job["metadata"]["name"],
            },
        )
        existing_setup = self.api.get(
            "Job", cr.namespace, setup_job["metadata"]["name"]
        )
        if existing_setup is None:
            self.api.apply(setup_job)
            return self._set_status(cr_dict, DEPLOYING, "setup job created")
        if not _job_succeeded(existing_setup):
            return self._set_status(cr_dict, DEPLOYING, "waiting for setup job")

        existing_deployer = self.api.get(
            "Job", cr.namespace, deployer_job["metadata"]["name"]
        )
        if existing_deployer is None:
            self.api.apply(deployer_job)
            return self._set_status(cr_dict, DEPLOYING, "deployer job created")
        if not _job_succeeded(existing_deployer):
            return self._set_status(cr_dict, DEPLOYING, "waiting for deployer job")
        return self._set_status(cr_dict, DEPLOYED, "deployed")

    def cleanup(self, cr_dict: dict[str, Any]) -> str:
        """Delete path: run the deployer job with ``delete`` to tear down
        Agent CRs, then remove every job and the config Secret."""
        cr = ApplicationCustomResource.from_dict(cr_dict)
        config_secret = f"{cr.name}-app-config"
        delete_job = AppResourcesFactory.generate_deployer_job(
            cr.spec.tenant, cr.name, cr.namespace, cr.spec.image,
            config_secret, delete=True,
        )
        existing = self.api.get("Job", cr.namespace, delete_job["metadata"]["name"])
        if existing is None:
            self.api.apply(delete_job)
            return DELETING
        if not _job_succeeded(existing):
            return DELETING
        self._prune_stale_jobs(cr, keep=set())
        # the config Secret carries the full app (incl. secrets YAML) —
        # never leave it behind
        self.api.delete("Secret", cr.namespace, config_secret)
        return "DELETED"

    def _set_status(self, cr_dict: dict[str, Any], phase: str, reason: str) -> str:
        current = (cr_dict.get("status") or {})
        if current.get("status") != phase or current.get("reason") != reason:
            self.api.update_status(
                {**cr_dict, "status": {"status": phase, "reason": reason}}
            )
        return phase


def _job_succeeded(job: dict[str, Any]) -> bool:
    return ((job.get("status") or {}).get("succeeded") or 0) >= 1


class Operator:
    """Poll-based reconcile loop over all namespaces.

    The reference uses informer-driven reconciliation with leader election;
    here a single loop lists CRs on an interval — the reconcilers themselves
    are level-triggered and idempotent, so missed events only cost latency.
    Infinite retry: reconcile failures are logged and retried next tick.
    """

    def __init__(
        self,
        api: KubeApi,
        interval: float = 2.0,
        accelerator: str = "v5e",
        watch: bool = False,
    ):
        self.api = api
        self.interval = interval
        self.apps = AppController(api)
        self.agents = AgentController(api, accelerator=accelerator)
        self._stop = asyncio.Event()
        # watch mode: CR events wake the loop immediately instead of
        # waiting out the poll interval (the poll remains as the resync
        # backstop — informer semantics without an informer cache)
        self.watch = watch and hasattr(api, "watch")
        self._wake: asyncio.Event = asyncio.Event()
        self._watch_threads: list = []

    def reconcile_once(self) -> dict[str, str]:
        statuses: dict[str, str] = {}
        for cr in self.api.list("Application"):
            name = cr["metadata"]["name"]
            try:
                statuses[f"app/{name}"] = self.apps.reconcile(cr)
            except Exception as e:  # infinite retry: next tick
                log.exception("app reconcile failed for %s", name)
                statuses[f"app/{name}"] = f"RETRY: {e}"
        for cr in self.api.list("Agent"):
            name = cr["metadata"]["name"]
            try:
                statuses[f"agent/{name}"] = self.agents.reconcile(cr)
            except Exception as e:
                log.exception("agent reconcile failed for %s", name)
                statuses[f"agent/{name}"] = f"RETRY: {e}"
        return statuses

    def _start_watchers(self, loop: asyncio.AbstractEventLoop) -> None:
        import threading

        def _watch_kind(kind: str) -> None:
            while not self._stop.is_set():
                try:
                    for _event, _obj in self.api.watch(kind, timeout_s=30):
                        if self._stop.is_set():
                            return
                        loop.call_soon_threadsafe(self._wake.set)
                except Exception:
                    # watch streams are best-effort wake-ups; the poll
                    # backstop guarantees progress — back off and redial
                    if self._stop.is_set():
                        return
                    time.sleep(1.0)

        for kind in ("Application", "Agent"):
            t = threading.Thread(
                target=_watch_kind, args=(kind,),
                name=f"operator-watch-{kind}", daemon=True,
            )
            t.start()
            self._watch_threads.append(t)

    async def run(self) -> None:
        if self.watch:
            self._start_watchers(asyncio.get_running_loop())
        while not self._stop.is_set():
            self._wake.clear()
            await asyncio.get_running_loop().run_in_executor(
                None, self.reconcile_once
            )
            stop_task = asyncio.ensure_future(self._stop.wait())
            wake_task = asyncio.ensure_future(self._wake.wait())
            try:
                await asyncio.wait(
                    {stop_task, wake_task},
                    timeout=self.interval,
                    return_when=asyncio.FIRST_COMPLETED,
                )
            finally:
                stop_task.cancel()
                wake_task.cancel()

    def stop(self) -> None:
        self._stop.set()


def main() -> None:
    """Operator service entrypoint (the deploy manifests run
    ``python -m langstream_tpu.k8s.operator``). Env: ``LS_ACCELERATOR``
    (v5e|v5p|v4), ``LS_RECONCILE_INTERVAL`` seconds."""
    import os
    import signal

    from langstream_tpu.k8s.client import HttpKubeApi

    logging.basicConfig(level=logging.INFO)
    operator = Operator(
        HttpKubeApi.in_cluster(),
        interval=float(os.environ.get("LS_RECONCILE_INTERVAL", "2.0")),
        accelerator=os.environ.get("LS_ACCELERATOR", "v5e"),
        watch=os.environ.get("LS_OPERATOR_WATCH", "1") != "0",
    )

    async def _run() -> None:
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, operator.stop)
        await operator.run()

    asyncio.run(_run())


if __name__ == "__main__":
    main()
