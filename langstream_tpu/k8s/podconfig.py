"""Runtime pod configuration: what an agent pod needs to boot one replica.

Parity: ``RuntimePodConfiguration(input, output, agent, streamingCluster)``
(``langstream-runtime-api/.../agent/RuntimePodConfiguration.java:21``) — the
deployer serializes this per agent into the agent-config Secret; the pod
entrypoint (:mod:`langstream_tpu.runtime.pod`) deserializes it and rebuilds
the minimal plan/node pair the :class:`AgentRunner` runs on.
"""

from __future__ import annotations

from typing import Any

from langstream_tpu.api.application import (
    AgentConfiguration,
    Application,
    ErrorsSpec,
    Instance,
    Resource,
    ResourcesSpec,
    StreamingCluster,
)
from langstream_tpu.api.execution_plan import AgentNode, Connection, ExecutionPlan


def pod_configuration(plan: ExecutionPlan, node: AgentNode) -> dict[str, Any]:
    """Serialize one agent node + its application context for a pod."""
    app = plan.application
    return {
        "applicationId": plan.application_id,
        "input": (
            {
                "topic": node.input.topic,
                "deadletter": node.input.deadletter_enabled,
            }
            if node.input
            else None
        ),
        "output": {"topic": node.output.topic} if node.output else None,
        "agent": {
            "id": node.id,
            "type": node.agent_type,
            "componentType": node.component_type,
            "configuration": node.configuration,
            "agents": [
                {
                    "id": a.id,
                    "name": a.name,
                    "type": a.type,
                    "configuration": a.configuration,
                }
                for a in node.agents
            ],
            "errors": {
                "retries": node.errors.retries,
                "on-failure": node.errors.on_failure,
            },
            "resources": {
                "parallelism": node.resources.parallelism,
                "size": node.resources.size,
                "device-mesh": node.resources.device_mesh,
            },
        },
        "streamingCluster": {
            "type": app.instance.streaming_cluster.type,
            "configuration": app.instance.streaming_cluster.configuration,
        },
        # ambient context agents resolve at init time
        "resources": {
            rid: {"type": r.type, "name": r.name, "configuration": r.configuration}
            for rid, r in app.resources.items()
        },
        "globals": app.instance.globals_,
    }


def plan_and_node(config: dict[str, Any]) -> tuple[ExecutionPlan, AgentNode]:
    """Rebuild the (plan, node) pair a pod's AgentRunner needs."""
    agent = config["agent"]
    node = AgentNode(
        id=agent["id"],
        agent_type=agent["type"],
        component_type=agent.get("componentType", "PROCESSOR"),
        input=(
            Connection(
                topic=config["input"]["topic"],
                deadletter_enabled=bool(config["input"].get("deadletter")),
            )
            if config.get("input")
            else None
        ),
        output=(
            Connection(topic=config["output"]["topic"])
            if config.get("output")
            else None
        ),
        agents=[
            AgentConfiguration(
                id=a["id"],
                name=a.get("name", a["id"]),
                type=a["type"],
                configuration=a.get("configuration") or {},
            )
            for a in agent.get("agents", [])
        ],
        resources=ResourcesSpec.from_dict(agent.get("resources")),
        errors=ErrorsSpec.from_dict(agent.get("errors")) or ErrorsSpec(),
        configuration=agent.get("configuration") or {},
    )
    streaming = config.get("streamingCluster") or {}
    app = Application(
        instance=Instance(
            streaming_cluster=StreamingCluster(
                type=streaming.get("type", "memory"),
                configuration=streaming.get("configuration") or {},
            ),
            globals_=config.get("globals") or {},
        ),
        resources={
            rid: Resource(
                id=rid,
                name=r.get("name", rid),
                type=r.get("type", ""),
                configuration=r.get("configuration") or {},
            )
            for rid, r in (config.get("resources") or {}).items()
        },
    )
    plan = ExecutionPlan(
        application_id=config.get("applicationId", "app"),
        application=app,
        agents={node.id: node},
    )
    return plan, node
