"""Manifest factories: CR → StatefulSet/Service/Job dicts.

Parity: ``AgentResourcesFactory.generateStatefulSet``
(``langstream-k8s-deployer-core/.../agents/AgentResourcesFactory.java:138``)
— init container ``agent-code-download`` (``:201``), main container
``agent-runtime`` (``:277``), PVC templates for agent disks, headless Service
per agent (``:98``) — and ``AppResourcesFactory.generateSetupJob`` /
``generateDeployerJob`` (``.../apps/AppResourcesFactory.java:231,76``).

TPU-first scheduling (the departure from the reference):

- an agent whose ``resources.device-mesh`` is set gets GKE TPU node-pool
  placement: ``google.com/tpu`` chip requests plus
  ``cloud.google.com/gke-tpu-accelerator`` / ``gke-tpu-topology`` selectors
  derived from the mesh's chip count;
- a mesh larger than one host's chips makes the logical replica a
  *multi-host slice*: the factory emits one StatefulSet per logical replica
  whose ``hosts`` pods form a JAX distributed process group — ordinal 0 is
  the coordinator, discovered through the headless service; the pod
  entrypoint turns ordinals into ``jax.distributed.initialize`` arguments.
  Data-parallel fan-out (``parallelism``) stays partition-based, exactly like
  the reference's replicas.
"""

from __future__ import annotations

from typing import Any

from langstream_tpu.k8s.crds import AgentCustomResource

AGENT_PORT = 8080  # /metrics + /info + /healthz + /ready (runtime/pod.py)
AGENT_SERVICE_PORT = 8790  # custom service agents (gateway agent-proxy target)
COORDINATOR_PORT = 8476  # jax.distributed coordinator
LOCKSTEP_PORT = 7077  # leader->follower step-descriptor channel (serving/lockstep.py)


# accelerator → (GKE accelerator label, chips per host, topology by chips)
TPU_TOPOLOGIES: dict[str, tuple[str, int, dict[int, str]]] = {
    "v5e": (
        "tpu-v5-lite-podslice",
        4,
        {1: "1x1", 4: "2x2", 8: "2x4", 16: "4x4", 32: "4x8", 64: "8x8",
         128: "8x16", 256: "16x16"},
    ),
    "v5p": (
        "tpu-v5p-slice",
        4,
        {4: "2x2x1", 8: "2x2x2", 16: "2x2x4", 32: "2x4x4", 64: "4x4x4",
         128: "4x4x8", 256: "4x8x8"},
    ),
    "v4": (
        "tpu-v4-podslice",
        4,
        {4: "2x2x1", 8: "2x2x2", 16: "2x2x4", 32: "2x4x4", 64: "4x4x4"},
    ),
}


def _lockstep_token(spec: Any) -> str:
    """Join token for the lockstep channel: HMAC of the slice identity keyed
    by the agent config checksum (cluster-internal secret material)."""
    import hashlib
    import hmac as _hmac

    key = (spec.agent_config_secret_ref_checksum or "unconfigured").encode()
    msg = f"{spec.tenant}/{spec.application_id}/{spec.agent_id}".encode()
    return _hmac.new(key, msg, hashlib.sha256).hexdigest()


def mesh_chips(device_mesh: dict[str, int] | None) -> int:
    chips = 1
    for axis_size in (device_mesh or {}).values():
        chips *= int(axis_size)
    return chips if device_mesh else 0


def tpu_placement(accelerator: str, chips: int) -> dict[str, Any]:
    """Node selectors + per-pod chip request for one slice of ``chips``."""
    if accelerator not in TPU_TOPOLOGIES:
        raise ValueError(
            f"unknown TPU accelerator {accelerator!r}; known: "
            f"{sorted(TPU_TOPOLOGIES)}"
        )
    label, chips_per_host, topologies = TPU_TOPOLOGIES[accelerator]
    if chips not in topologies:
        raise ValueError(
            f"no {accelerator} topology for {chips} chips; available: "
            f"{sorted(topologies)}"
        )
    hosts = max(1, chips // chips_per_host)
    return {
        "hosts": hosts,
        "chips_per_pod": min(chips, chips_per_host),
        "node_selector": {
            "cloud.google.com/gke-tpu-accelerator": label,
            "cloud.google.com/gke-tpu-topology": topologies[chips],
        },
    }


class AgentResourcesFactory:
    """Turns one Agent CR into StatefulSet(s) + headless Service + PDB
    manifests."""

    #: grace budget the preStop /drain hands the serving engines; the pod
    #: terminationGracePeriod is sized above it so the kubelet never
    #: SIGKILLs a pod mid-requeue
    DRAIN_GRACE_S = 45
    TERMINATION_GRACE_S = 90

    @staticmethod
    def agent_resource_name(application_id: str, agent_id: str) -> str:
        return f"{application_id}-{agent_id}".lower().replace("_", "-")

    @classmethod
    def generate_headless_service(cls, cr: AgentCustomResource) -> dict[str, Any]:
        name = cls.agent_resource_name(cr.spec.application_id, cr.spec.agent_id)
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": name,
                "namespace": cr.namespace,
                "labels": _agent_labels(cr),
            },
            "spec": {
                "clusterIP": "None",
                # slice bootstrap: followers must resolve the coordinator
                # pod's DNS *before* it is Ready (jax.distributed.initialize
                # blocks until every host joins, which is itself gated on
                # this DNS) — without this flag multi-host startup deadlocks
                "publishNotReadyAddresses": True,
                "selector": _agent_labels(cr),
                "ports": [
                    {"name": "http", "port": AGENT_PORT},
                    {"name": "coordinator", "port": COORDINATOR_PORT},
                    {"name": "lockstep", "port": LOCKSTEP_PORT},
                    # custom service agents listen here; the api-gateway's
                    # agent-proxy mode forwards to this port by service name
                    {"name": "agent-service", "port": AGENT_SERVICE_PORT},
                ],
            },
        }

    @staticmethod
    def pool_roles(cr: AgentCustomResource) -> dict[str, int] | None:
        """The agent's declared disaggregated pools (docs/DISAGG.md):
        ``{role: replicas}`` from the CR's ``poolRoles`` option — a list
        (``[prefill, decode]``, parallelism replicas each) or a mapping
        (``{prefill: 1, decode: 3}``). None = classic combined serving.
        Raises ValueError on unknown roles — a bad split must fail the
        reconcile loudly, not deploy one mislabeled fleet."""
        declared = (cr.spec.options or {}).get("poolRoles") or (
            cr.spec.options or {}
        ).get("pool-roles")
        if not declared:
            return None
        parallelism = max(1, cr.spec.resources.parallelism)
        if isinstance(declared, dict):
            roles = {str(k): max(1, int(v)) for k, v in declared.items()}
        else:
            roles = {str(r): parallelism for r in declared}
        unknown = sorted(set(roles) - {"prefill", "decode"})
        if unknown:
            raise ValueError(
                f"unknown pool role(s) {unknown}; known: prefill, decode"
            )
        return roles

    @classmethod
    def generate_statefulsets(
        cls,
        cr: AgentCustomResource,
        accelerator: str = "v5e",
        image_pull_policy: str = "IfNotPresent",
    ) -> list[dict[str, Any]]:
        """One STS for single-host agents (replicas = parallelism); one STS
        *per logical replica* for multi-host slices (replicas = hosts);
        one STS *per pool role* for disaggregated serving agents
        (``poolRoles`` option — docs/DISAGG.md): ``<name>-prefill`` /
        ``<name>-decode``, each pod told its role via ``LS_POOL_ROLE``
        so both pools share one agent config secret."""
        chips = mesh_chips(cr.spec.resources.device_mesh)
        parallelism = max(1, cr.spec.resources.parallelism)
        base = cls.agent_resource_name(cr.spec.application_id, cr.spec.agent_id)
        service = base
        pools = cls.pool_roles(cr)

        if chips == 0:
            if pools:
                return [
                    cls._statefulset(
                        cr, name=f"{base}-{role}", service=service,
                        replicas=replicas, placement=None,
                        image_pull_policy=image_pull_policy,
                        logical_replica=None, pool_role=role,
                    )
                    for role, replicas in sorted(pools.items())
                ]
            return [
                cls._statefulset(
                    cr, name=base, service=service, replicas=parallelism,
                    placement=None, image_pull_policy=image_pull_policy,
                    logical_replica=None,
                )
            ]

        placement = tpu_placement(accelerator, chips)
        if placement["hosts"] == 1:
            if pools:
                return [
                    cls._statefulset(
                        cr, name=f"{base}-{role}", service=service,
                        replicas=replicas, placement=placement,
                        image_pull_policy=image_pull_policy,
                        logical_replica=None, pool_role=role,
                    )
                    for role, replicas in sorted(pools.items())
                ]
            return [
                cls._statefulset(
                    cr, name=base, service=service, replicas=parallelism,
                    placement=placement, image_pull_policy=image_pull_policy,
                    logical_replica=None,
                )
            ]
        if pools:
            # a multi-host slice's STS replica count is the slice's HOST
            # count — there is no per-pool replica axis to split on
            raise ValueError(
                "poolRoles is not supported on multi-host slices: the "
                "slice's StatefulSet replicas are its hosts, not serving "
                "capacity (scale pools as single-host agents)"
            )
        # multi-host: parallelism logical replicas × hosts pods each
        return [
            cls._statefulset(
                cr, name=f"{base}-r{i}", service=service,
                replicas=placement["hosts"], placement=placement,
                image_pull_policy=image_pull_policy, logical_replica=i,
            )
            for i in range(parallelism)
        ]

    @classmethod
    def generate_pod_disruption_budgets(
        cls,
        cr: AgentCustomResource,
        statefulsets: list[dict[str, Any]] | None = None,
        accelerator: str = "v5e",
    ) -> list[dict[str, Any]]:
        """One PDB per StatefulSet, ``maxUnavailable: 1``: voluntary
        evictions (node drains, cluster upgrades) take pods one at a
        time, and each eviction runs the same preStop ``/drain`` path
        the autoscaler's scale-down uses — so a node rotation requeues
        in-flight generations instead of dropping a whole fleet at
        once. Involuntary disruptions (node death) bypass PDBs by
        definition; crash-requeue (ROADMAP item 5) is that lane. Pass
        the already-generated ``statefulsets`` to avoid regenerating
        them (the operator does)."""
        if statefulsets is None:
            statefulsets = cls.generate_statefulsets(
                cr, accelerator=accelerator
            )
        return [
            {
                "apiVersion": "policy/v1",
                "kind": "PodDisruptionBudget",
                "metadata": {
                    "name": sts["metadata"]["name"],
                    "namespace": cr.namespace,
                    "labels": _agent_labels(cr),
                },
                "spec": {
                    "maxUnavailable": 1,
                    "selector": sts["spec"]["selector"],
                },
            }
            for sts in statefulsets
        ]

    @classmethod
    def _statefulset(
        cls,
        cr: AgentCustomResource,
        name: str,
        service: str,
        replicas: int,
        placement: dict[str, Any] | None,
        image_pull_policy: str,
        logical_replica: int | None,
        pool_role: str | None = None,
    ) -> dict[str, Any]:
        spec = cr.spec
        env = [
            {"name": "LS_APPLICATION_ID", "value": spec.application_id},
            {"name": "LS_AGENT_ID", "value": spec.agent_id},
            {"name": "LS_TENANT", "value": spec.tenant},
            {
                "name": "LS_POD_NAME",
                "valueFrom": {"fieldRef": {"fieldPath": "metadata.name"}},
            },
            # total logical replicas of this agent: runtimes with static
            # partition assignment (wire kafka) split partitions on
            # (ordinal, this) when the runner config doesn't already say
            {
                "name": "LS_NUM_REPLICAS",
                "value": str(max(1, spec.resources.parallelism)),
            },
        ]
        resources: dict[str, Any] = {
            "requests": {
                "cpu": f"{spec.resources.size * 0.5}",
                "memory": f"{spec.resources.size * 512}M",
            }
        }
        if placement:
            chips = placement["chips_per_pod"]
            resources.setdefault("limits", {})["google.com/tpu"] = str(chips)
            resources["requests"]["google.com/tpu"] = str(chips)
            env += [
                {"name": "LS_SLICE_HOSTS", "value": str(placement["hosts"])},
                {
                    "name": "LS_COORDINATOR_ADDRESS",
                    "value": f"{name}-0.{service}:{COORDINATOR_PORT}",
                },
                # lockstep control channel: followers replay the leader's
                # jitted dispatches from this port (serving/lockstep.py)
                {"name": "LS_LOCKSTEP_PORT", "value": str(LOCKSTEP_PORT)},
                # join auth for the channel: deterministic (a random value
                # would diff the spec and roll the pods every reconcile) but
                # derived from the config-secret checksum, which only pods
                # holding the mounted config know
                {"name": "LS_LOCKSTEP_TOKEN", "value": _lockstep_token(spec)},
            ]
        if logical_replica is not None:
            env.append(
                {"name": "LS_LOGICAL_REPLICA", "value": str(logical_replica)}
            )
        if pool_role is not None:
            # disaggregated pools (docs/DISAGG.md): both pool STSs mount
            # the SAME agent config secret; the role is per-StatefulSet
            # deployment identity, so it rides the env and
            # ServingConfig.from_dict picks it up as the pool-role
            # fallback
            env.append({"name": "LS_POOL_ROLE", "value": pool_role})

        volume_mounts = [
            {"name": "app-config", "mountPath": "/app-config"},
            {"name": "app-code-download", "mountPath": "/app-code-download"},
        ]
        volumes: list[dict[str, Any]] = [
            {
                "name": "app-config",
                "secret": {"secretName": spec.agent_config_secret_ref},
            },
            {"name": "app-code-download", "emptyDir": {}},
        ]
        volume_claim_templates: list[dict[str, Any]] = []
        if spec.disk is not None and spec.disk.enabled:
            volume_mounts.append(
                {"name": "agent-state", "mountPath": "/agent-state"}
            )
            claim: dict[str, Any] = {
                "metadata": {"name": "agent-state"},
                "spec": {
                    "accessModes": ["ReadWriteOnce"],
                    "resources": {"requests": {"storage": spec.disk.size}},
                },
            }
            if spec.disk.type != "default":
                claim["spec"]["storageClassName"] = spec.disk.type
            volume_claim_templates.append(claim)

        entrypoint = ["python", "-m", "langstream_tpu.runtime.pod"]
        pod_spec: dict[str, Any] = {
            # must exceed the preStop /drain grace (DRAIN_GRACE_S) plus
            # the runner's own broker-drain budget: the kubelet SIGKILLs
            # at this deadline no matter what preStop is still doing
            "terminationGracePeriodSeconds": cls.TERMINATION_GRACE_S,
            "initContainers": [
                {
                    "name": "code-download",
                    "image": spec.image,
                    "imagePullPolicy": image_pull_policy,
                    "command": entrypoint
                    + ["agent-code-download", "/app-config/config",
                       "/app-code-download"],
                    "volumeMounts": volume_mounts,
                }
            ],
            "containers": [
                {
                    "name": "runtime",
                    "image": spec.image,
                    "imagePullPolicy": image_pull_policy,
                    "command": entrypoint
                    + ["agent-runtime", "/app-config/config",
                       "/app-code-download"],
                    "env": env,
                    "ports": [
                        {"name": "http", "containerPort": AGENT_PORT},
                        {"name": "coordinator", "containerPort": COORDINATOR_PORT},
                    ],
                    "resources": resources,
                    "volumeMounts": volume_mounts,
                    # readiness gates on the REAL serving surface
                    # (runtime/pod.py /ready: agent init done, engines
                    # warmed, nothing wedged) — /info answers 200 the
                    # instant the HTTP server binds, before agents
                    # initialize and forever after the device wedges, so
                    # probing it routed traffic to pods that could not
                    # serve (/info itself stays for the CLI)
                    "readinessProbe": {
                        "httpGet": {"path": "/ready", "port": AGENT_PORT},
                        "initialDelaySeconds": 5,
                        "periodSeconds": 10,
                    },
                    # liveness fails only on a WEDGED engine (no step
                    # progress while work is queued, serving/health.py):
                    # ~3 failures x 10 s after the watchdog window a
                    # wedged device finally gets the pod rescheduled.
                    # initialDelay + the 60 s default wedge window keep
                    # first-compile convoys from reading as death
                    "livenessProbe": {
                        "httpGet": {"path": "/healthz", "port": AGENT_PORT},
                        "initialDelaySeconds": 30,
                        "periodSeconds": 10,
                        "failureThreshold": 3,
                    },
                    # drain-before-terminate (docs/FLEET.md): every
                    # voluntary termination — autoscaler scale-down,
                    # rolling update, node drain honoring the PDB —
                    # first stops admission and requeues in-flight
                    # generations through /drain; the endpoint blocks
                    # until the engines settle, and the kubelet holds
                    # SIGTERM until preStop returns (within the
                    # terminationGracePeriod above)
                    "lifecycle": {
                        "preStop": {
                            "httpGet": {
                                "path": (
                                    f"/drain?grace-s={cls.DRAIN_GRACE_S}"
                                ),
                                "port": AGENT_PORT,
                            }
                        }
                    },
                }
            ],
            "volumes": volumes,
        }
        if placement:
            pod_spec["nodeSelector"] = placement["node_selector"]

        return {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {
                "name": name,
                "namespace": cr.namespace,
                "labels": _agent_labels(cr),
            },
            "spec": {
                "serviceName": service,
                "replicas": replicas,
                "podManagementPolicy": "Parallel",
                "selector": {"matchLabels": {**_agent_labels(cr), "sts": name}},
                "template": {
                    "metadata": {
                        "labels": {**_agent_labels(cr), "sts": name},
                        "annotations": {
                            # config rollout trigger (parity: checksum on the
                            # agent-config Secret ref)
                            "langstream.tpu/config-checksum": (
                                spec.agent_config_secret_ref_checksum
                            ),
                            "prometheus.io/scrape": "true",
                            "prometheus.io/port": str(AGENT_PORT),
                            "prometheus.io/path": "/metrics",
                        },
                    },
                    "spec": pod_spec,
                },
                "volumeClaimTemplates": volume_claim_templates,
            },
        }


def _agent_labels(cr: AgentCustomResource) -> dict[str, str]:
    return {
        "app": "langstream-tpu-runtime",
        "langstream-application": cr.spec.application_id,
        "langstream-agent": cr.spec.agent_id,
    }


class AppResourcesFactory:
    """Setup/deployer Job manifests (the in-cluster halves of deploy)."""

    @staticmethod
    def _job(
        name: str,
        namespace: str,
        image: str,
        args: list[str],
        config_secret: str,
        labels: dict[str, str],
    ) -> dict[str, Any]:
        return {
            "apiVersion": "batch/v1",
            "kind": "Job",
            "metadata": {"name": name, "namespace": namespace, "labels": labels},
            "spec": {
                "backoffLimit": 6,
                "template": {
                    "metadata": {"labels": labels},
                    "spec": {
                        "restartPolicy": "OnFailure",
                        "containers": [
                            {
                                "name": "main",
                                "image": image,
                                "command": [
                                    "python", "-m", "langstream_tpu.runtime.pod",
                                ] + args,
                                "volumeMounts": [
                                    {
                                        "name": "app-config",
                                        "mountPath": "/app-config",
                                    }
                                ],
                            }
                        ],
                        "volumes": [
                            {
                                "name": "app-config",
                                "secret": {"secretName": config_secret},
                            }
                        ],
                    },
                },
            },
        }

    @classmethod
    def generate_setup_job(
        cls, tenant: str, application_id: str, namespace: str, image: str,
        config_secret: str, name_suffix: str = "",
    ) -> dict[str, Any]:
        """Creates topics + provisions assets (pod command
        ``application-setup``; parity ``AppResourcesFactory.java:231``).
        ``name_suffix`` ties the Job's identity to the app-config checksum so
        an updated application re-runs setup (Jobs are immutable-ish)."""
        return cls._job(
            name=f"langstream-runtime-setup-{application_id}{name_suffix}",
            namespace=namespace,
            image=image,
            args=["application-setup", "setup", "/app-config/config"],
            config_secret=config_secret,
            labels={
                "app": "langstream-tpu-setup",
                "langstream-application": application_id,
            },
        )

    @classmethod
    def generate_deployer_job(
        cls, tenant: str, application_id: str, namespace: str, image: str,
        config_secret: str, delete: bool = False, name_suffix: str = "",
    ) -> dict[str, Any]:
        """Plans the app in-cluster and writes/deletes Agent CRs (pod command
        ``deployer-runtime``; parity ``AppResourcesFactory.java:76``)."""
        action = "delete" if delete else "deploy"
        return cls._job(
            name=f"langstream-runtime-deployer-{action}-{application_id}"
            f"{name_suffix}",
            namespace=namespace,
            image=image,
            args=["deployer-runtime", action, "/app-config/config"],
            config_secret=config_secret,
            labels={
                "app": "langstream-tpu-deployer",
                "langstream-application": application_id,
            },
        )
