"""Kubernetes-backed application + tenant stores.

Parity: ``langstream-k8s-storage`` — ``KubernetesApplicationStore`` (app
definitions as Application CRs + Secrets in per-tenant namespaces
``langstream-<tenant>``; ``KubernetesApplicationStore.java:67,138,201``) and
``KubernetesGlobalMetadataStore`` (tenants as ConfigMaps). Implements the
same :class:`ApplicationStore` ABC the control plane already uses for its
in-memory and filesystem stores, so the webservice swaps stores by config.
"""

from __future__ import annotations

import base64
import json
from typing import Any

from langstream_tpu.controlplane.stores import (
    ApplicationStore,
    StoredApplication,
    validate_filenames,
)
from langstream_tpu.k8s.client import KubeApi
from langstream_tpu.k8s.cluster_runtime import tenant_namespace
from langstream_tpu.k8s.crds import (
    ApplicationCustomResource,
    ApplicationSpec,
)

GLOBAL_NAMESPACE = "langstream-system"
TENANT_CM_PREFIX = "langstream-tenant-"


class KubernetesApplicationStore(ApplicationStore):
    def __init__(self, api: KubeApi, runtime_image: str = "",
                 code_storage_config: dict | None = None):
        self.api = api
        self.runtime_image = runtime_image
        # flows into ApplicationSpec.options so the operator's setup/
        # deployer Jobs know where archives live (AppController reads
        # options.codeStorage into the job config document)
        self.code_storage_config = code_storage_config

    # ---- tenants (GlobalMetadataStore role) ------------------------------

    def put_tenant(self, tenant: str, config: dict[str, Any] | None = None) -> None:
        self.api.apply(
            {
                "apiVersion": "v1",
                "kind": "Namespace",
                "metadata": {
                    "name": tenant_namespace(tenant),
                    "labels": {"app": "langstream-tpu", "langstream-tenant": tenant},
                },
            }
        )
        self.api.apply(
            {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "metadata": {
                    "name": f"{TENANT_CM_PREFIX}{tenant}",
                    "namespace": GLOBAL_NAMESPACE,
                    "labels": {"app": "langstream-tpu-tenant"},
                },
                "data": {"tenant": json.dumps(config or {})},
            }
        )

    def delete_tenant(self, tenant: str) -> None:
        self.api.delete(
            "ConfigMap", GLOBAL_NAMESPACE, f"{TENANT_CM_PREFIX}{tenant}"
        )
        self.api.delete("Namespace", None, tenant_namespace(tenant))

    def list_tenants(self) -> dict[str, dict[str, Any]]:
        out: dict[str, dict[str, Any]] = {}
        for cm in self.api.list(
            "ConfigMap", GLOBAL_NAMESPACE,
            label_selector={"app": "langstream-tpu-tenant"},
        ):
            name = cm["metadata"]["name"]
            if name.startswith(TENANT_CM_PREFIX):
                out[name[len(TENANT_CM_PREFIX):]] = json.loads(
                    (cm.get("data") or {}).get("tenant", "{}")
                )
        return out

    # ---- applications ----------------------------------------------------

    def put_application(self, app: StoredApplication) -> None:
        validate_filenames(app.files)
        namespace = tenant_namespace(app.tenant)
        serialized = json.dumps(
            {
                "files": app.files,
                "instance": app.instance,
                "created_at": app.created_at,
                "units": app.units,
            }
        )
        cr = ApplicationCustomResource(
            name=app.name,
            namespace=namespace,
            spec=ApplicationSpec(
                tenant=app.tenant,
                image=self.runtime_image,
                application=serialized,
                code_archive_id=app.code_archive_id,
                options=(
                    {"codeStorage": self.code_storage_config}
                    if self.code_storage_config else {}
                ),
            ),
            status={"status": app.status, "error": app.error},
        )
        self.api.apply(cr.to_dict())
        self.api.update_status(cr.to_dict())
        # secrets live in a Secret next to the CR, never inside it
        # (parity: KubernetesApplicationStore.java:201)
        self.api.apply(
            {
                "apiVersion": "v1",
                "kind": "Secret",
                "metadata": {
                    "name": f"{app.name}-secrets",
                    "namespace": namespace,
                    "labels": {"langstream-application": app.name},
                },
                "data": {
                    "secrets": base64.b64encode(
                        (app.secrets or "").encode()
                    ).decode()
                },
            }
        )

    def get_application(self, tenant: str, name: str) -> StoredApplication | None:
        namespace = tenant_namespace(tenant)
        cr_dict = self.api.get("Application", namespace, name)
        if cr_dict is None:
            return None
        cr = ApplicationCustomResource.from_dict(cr_dict)
        payload = json.loads(cr.spec.application or "{}")
        secret = self.api.get("Secret", namespace, f"{name}-secrets")
        secrets = None
        if secret is not None:
            raw = (secret.get("data") or {}).get("secrets", "")
            secrets = base64.b64decode(raw).decode() if raw else None
        return StoredApplication(
            tenant=tenant,
            name=name,
            files=payload.get("files") or {},
            instance=payload.get("instance"),
            secrets=secrets or None,
            status=(cr.status or {}).get("status", "CREATED"),
            error=(cr.status or {}).get("error"),
            created_at=payload.get("created_at", 0),
            units=int(payload.get("units", 0)),
        )

    def delete_application(self, tenant: str, name: str) -> None:
        namespace = tenant_namespace(tenant)
        self.api.delete("Application", namespace, name)
        self.api.delete("Secret", namespace, f"{name}-secrets")

    def list_applications(self, tenant: str) -> list[str]:
        return sorted(
            cr["metadata"]["name"]
            for cr in self.api.list("Application", tenant_namespace(tenant))
        )
