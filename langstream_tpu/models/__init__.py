"""JAX model zoo: the in-tree replacements for the reference's SaaS models.

The reference delegates completions/embeddings to external HTTP APIs
(``langstream-ai-agents/.../services/impl/*``); here the models live in-tree
as pure-JAX functional implementations designed for the MXU: stacked-layer
parameters scanned with ``lax.scan`` (one compiled layer body), bfloat16
weights, static shapes, and ``NamedSharding`` rules for tensor parallelism.
"""

from langstream_tpu.models.llama import LlamaConfig, init_llama_params, llama_prefill, llama_decode_step
from langstream_tpu.models.encoder import EncoderConfig, init_encoder_params, encode

__all__ = [
    "LlamaConfig",
    "init_llama_params",
    "llama_prefill",
    "llama_decode_step",
    "EncoderConfig",
    "init_encoder_params",
    "encode",
]
