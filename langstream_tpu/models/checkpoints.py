"""Checkpoint loading for Llama-family weights (local files only).

Supports HF-format directories (``*.safetensors`` or ``pytorch_model*.bin``)
with standard Llama tensor names, converted into our stacked-layer layout.
No network egress exists in this environment, so loading is gated on the
files being present; the serving engine falls back to random init otherwise.
"""

from __future__ import annotations

from pathlib import Path

import jax.numpy as jnp
import numpy as np

from langstream_tpu.models.llama import LlamaConfig


def _load_state_dict(path: Path) -> dict:
    safetensors = sorted(path.glob("*.safetensors"))
    if safetensors:
        try:
            from safetensors.numpy import load_file
        except ImportError as e:
            raise RuntimeError(
                "checkpoint is in safetensors format but the safetensors "
                f"library is unavailable: {e}"
            )
        state: dict = {}
        for f in safetensors:
            state.update(load_file(str(f)))
        return state
    bins = sorted(path.glob("pytorch_model*.bin"))
    if bins:
        import torch

        state = {}
        for f in bins:
            part = torch.load(str(f), map_location="cpu")
            state.update({k: v.numpy() for k, v in part.items()})
        return state
    raise FileNotFoundError(f"no weight files under {path}")


def save_llama_checkpoint(
    params: dict, config: LlamaConfig, checkpoint_dir: str
) -> None:
    """Write a stacked-layer param tree back out as an HF-format Llama
    checkpoint (``pytorch_model.bin`` with standard tensor names plus a
    minimal ``config.json``) — the inverse of :func:`load_llama_checkpoint`,
    so checkpoints round-trip between this framework and the HF ecosystem."""
    import json

    import torch

    path = Path(checkpoint_dir)
    path.mkdir(parents=True, exist_ok=True)
    c = config
    layers = params["layers"]

    def t(a: np.ndarray, transpose: bool = True) -> "torch.Tensor":
        a = a.astype(np.float32, copy=False)
        return torch.from_numpy(a.T.copy() if transpose else a.copy())

    state: dict = {
        "model.embed_tokens.weight": t(
            np.asarray(params["embed"]), transpose=False
        ),
        "model.norm.weight": t(np.asarray(params["final_norm"]), transpose=False),
        "lm_head.weight": t(np.asarray(params["lm_head"])),
    }
    names = {
        "attn_norm": ("input_layernorm.weight", False),
        "wq": ("self_attn.q_proj.weight", True),
        "wk": ("self_attn.k_proj.weight", True),
        "wv": ("self_attn.v_proj.weight", True),
        "wo": ("self_attn.o_proj.weight", True),
        "mlp_norm": ("post_attention_layernorm.weight", False),
        "w_gate": ("mlp.gate_proj.weight", True),
        "w_up": ("mlp.up_proj.weight", True),
        "w_down": ("mlp.down_proj.weight", True),
    }
    # one device→host transfer per stacked tensor, indexed per layer after
    # (not L transfers of the full stack)
    host = {ours: np.asarray(layers[ours]) for ours in names}
    for i in range(c.layers):
        for ours, (hf_name, transpose) in names.items():
            state[f"model.layers.{i}.{hf_name}"] = t(
                host[ours][i], transpose=transpose
            )
    torch.save(state, path / "pytorch_model.bin")
    (path / "config.json").write_text(
        json.dumps(
            {
                "architectures": ["LlamaForCausalLM"],
                "model_type": "llama",
                "vocab_size": c.vocab_size,
                "hidden_size": c.hidden,
                "num_hidden_layers": c.layers,
                "num_attention_heads": c.heads,
                "num_key_value_heads": c.kv_heads,
                "head_dim": c.head_dim,
                "intermediate_size": c.intermediate,
                "rope_theta": c.rope_theta,
                "rms_norm_eps": c.norm_eps,
                "max_position_embeddings": c.max_seq_len,
                "tie_word_embeddings": False,
                "torch_dtype": "float32",
            },
            indent=2,
        )
    )


def load_llama_checkpoint(checkpoint_dir: str, config: LlamaConfig) -> dict:
    path = Path(checkpoint_dir)
    state = _load_state_dict(path)
    c = config
    dt = c.dtype

    def g(name: str) -> np.ndarray:
        key = name if name in state else f"model.{name}"
        return np.asarray(state[key])

    def stack(fmt: str, transpose: bool = True) -> jnp.ndarray:
        mats = []
        for i in range(c.layers):
            m = g(fmt.format(i=i))
            mats.append(m.T if transpose else m)
        return jnp.asarray(np.stack(mats), dtype=dt)

    return {
        "embed": jnp.asarray(g("embed_tokens.weight"), dtype=dt),
        "layers": {
            "attn_norm": stack("layers.{i}.input_layernorm.weight", transpose=False),
            "wq": stack("layers.{i}.self_attn.q_proj.weight"),
            "wk": stack("layers.{i}.self_attn.k_proj.weight"),
            "wv": stack("layers.{i}.self_attn.v_proj.weight"),
            "wo": stack("layers.{i}.self_attn.o_proj.weight"),
            "mlp_norm": stack("layers.{i}.post_attention_layernorm.weight", transpose=False),
            "w_gate": stack("layers.{i}.mlp.gate_proj.weight"),
            "w_up": stack("layers.{i}.mlp.up_proj.weight"),
            "w_down": stack("layers.{i}.mlp.down_proj.weight"),
        },
        "final_norm": jnp.asarray(g("norm.weight"), dtype=dt),
        "lm_head": jnp.asarray(
            np.asarray(state.get("lm_head.weight", g("embed_tokens.weight"))).T,
            dtype=dt,
        ),
    }
