"""Checkpoint loading for Llama-family weights (local files only).

Supports HF-format directories (``*.safetensors`` or ``pytorch_model*.bin``)
with standard Llama tensor names, converted into our stacked-layer layout.
No network egress exists in this environment, so loading is gated on the
files being present; the serving engine falls back to random init otherwise.
"""

from __future__ import annotations

from pathlib import Path

import jax.numpy as jnp
import numpy as np

from langstream_tpu.models.llama import LlamaConfig


def _load_state_dict(path: Path) -> dict:
    safetensors = sorted(path.glob("*.safetensors"))
    if safetensors:
        try:
            from safetensors.numpy import load_file
        except ImportError as e:
            raise RuntimeError(
                "checkpoint is in safetensors format but the safetensors "
                f"library is unavailable: {e}"
            )
        state: dict = {}
        for f in safetensors:
            state.update(load_file(str(f)))
        return state
    bins = sorted(path.glob("pytorch_model*.bin"))
    if bins:
        import torch

        state = {}
        for f in bins:
            part = torch.load(str(f), map_location="cpu")
            state.update({k: v.numpy() for k, v in part.items()})
        return state
    raise FileNotFoundError(f"no weight files under {path}")


def load_llama_checkpoint(checkpoint_dir: str, config: LlamaConfig) -> dict:
    path = Path(checkpoint_dir)
    state = _load_state_dict(path)
    c = config
    dt = c.dtype

    def g(name: str) -> np.ndarray:
        key = name if name in state else f"model.{name}"
        return np.asarray(state[key])

    def stack(fmt: str, transpose: bool = True) -> jnp.ndarray:
        mats = []
        for i in range(c.layers):
            m = g(fmt.format(i=i))
            mats.append(m.T if transpose else m)
        return jnp.asarray(np.stack(mats), dtype=dt)

    return {
        "embed": jnp.asarray(g("embed_tokens.weight"), dtype=dt),
        "layers": {
            "attn_norm": stack("layers.{i}.input_layernorm.weight", transpose=False),
            "wq": stack("layers.{i}.self_attn.q_proj.weight"),
            "wk": stack("layers.{i}.self_attn.k_proj.weight"),
            "wv": stack("layers.{i}.self_attn.v_proj.weight"),
            "wo": stack("layers.{i}.self_attn.o_proj.weight"),
            "mlp_norm": stack("layers.{i}.post_attention_layernorm.weight", transpose=False),
            "w_gate": stack("layers.{i}.mlp.gate_proj.weight"),
            "w_up": stack("layers.{i}.mlp.up_proj.weight"),
            "w_down": stack("layers.{i}.mlp.down_proj.weight"),
        },
        "final_norm": jnp.asarray(g("norm.weight"), dtype=dt),
        "lm_head": jnp.asarray(
            np.asarray(state.get("lm_head.weight", g("embed_tokens.weight"))).T,
            dtype=dt,
        ),
    }
