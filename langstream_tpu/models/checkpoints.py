"""Checkpoint loading for Llama- and Mixtral-family weights (local files).

Supports HF-format directories (``*.safetensors`` or ``pytorch_model*.bin``)
with standard Llama/Mixtral tensor names, converted into our stacked-layer
layout. No network egress exists in this environment, so loading is gated on
the files being present; the serving engine falls back to random init
otherwise.
"""

from __future__ import annotations

from pathlib import Path
from typing import TYPE_CHECKING

import jax.numpy as jnp
import numpy as np

from langstream_tpu.models.llama import LlamaConfig

if TYPE_CHECKING:
    from langstream_tpu.models.moe import MoEConfig


def _load_state_dict(path: Path) -> dict:
    safetensors = sorted(path.glob("*.safetensors"))
    if safetensors:
        try:
            from safetensors.numpy import load_file
        except ImportError as e:
            raise RuntimeError(
                "checkpoint is in safetensors format but the safetensors "
                f"library is unavailable: {e}"
            )
        state: dict = {}
        for f in safetensors:
            state.update(load_file(str(f)))
        return state
    bins = sorted(path.glob("pytorch_model*.bin"))
    if bins:
        import torch

        state = {}
        for f in bins:
            part = torch.load(str(f), map_location="cpu")
            state.update({k: v.numpy() for k, v in part.items()})
        return state
    raise FileNotFoundError(f"no weight files under {path}")


# shared HF↔ours conventions (used by both the Llama and Mixtral pairs —
# attention tensors are identical across the two families)

_ATTN_NAMES = {
    "attn_norm": ("input_layernorm.weight", False),
    "wq": ("self_attn.q_proj.weight", True),
    "wk": ("self_attn.k_proj.weight", True),
    "wv": ("self_attn.v_proj.weight", True),
    "wo": ("self_attn.o_proj.weight", True),
    "mlp_norm": ("post_attention_layernorm.weight", False),
}


def _torch_tensor(a: np.ndarray, transpose: bool = True):
    import torch

    a = a.astype(np.float32, copy=False)
    return torch.from_numpy(a.T.copy() if transpose else a.copy())


def _getter(state: dict):
    """Resolve a tensor by name, tolerating the ``model.`` prefix."""

    def g(name: str) -> np.ndarray:
        key = name if name in state else f"model.{name}"
        return np.asarray(state[key])

    return g


def _stack_layers(g, fmt: str, layers: int, dt, transpose: bool = True):
    mats = []
    for i in range(layers):
        m = g(fmt.format(i=i))
        mats.append(m.T if transpose else m)
    return jnp.asarray(np.stack(mats), dtype=dt)


def _load_attn_layers(g, layers: int, dt) -> dict:
    return {
        ours: _stack_layers(g, "layers.{i}." + hf, layers, dt, transpose)
        for ours, (hf, transpose) in _ATTN_NAMES.items()
    }


def _load_head_tensors(state: dict, g, dt) -> dict:
    return {
        "embed": jnp.asarray(g("embed_tokens.weight"), dtype=dt),
        "final_norm": jnp.asarray(g("norm.weight"), dtype=dt),
        "lm_head": jnp.asarray(
            np.asarray(state.get("lm_head.weight", g("embed_tokens.weight"))).T,
            dtype=dt,
        ),
    }


def _save_head_tensors(params: dict) -> dict:
    return {
        "model.embed_tokens.weight": _torch_tensor(
            np.asarray(params["embed"]), transpose=False
        ),
        "model.norm.weight": _torch_tensor(
            np.asarray(params["final_norm"]), transpose=False
        ),
        "lm_head.weight": _torch_tensor(np.asarray(params["lm_head"])),
    }


def save_llama_checkpoint(
    params: dict, config: LlamaConfig, checkpoint_dir: str
) -> None:
    """Write a stacked-layer param tree back out as an HF-format Llama
    checkpoint (``pytorch_model.bin`` with standard tensor names plus a
    minimal ``config.json``) — the inverse of :func:`load_llama_checkpoint`,
    so checkpoints round-trip between this framework and the HF ecosystem."""
    import json

    import torch

    path = Path(checkpoint_dir)
    path.mkdir(parents=True, exist_ok=True)
    c = config
    layers = params["layers"]

    state = _save_head_tensors(params)
    names = {
        **_ATTN_NAMES,
        "w_gate": ("mlp.gate_proj.weight", True),
        "w_up": ("mlp.up_proj.weight", True),
        "w_down": ("mlp.down_proj.weight", True),
    }
    # one device→host transfer per stacked tensor, indexed per layer after
    # (not L transfers of the full stack)
    host = {ours: np.asarray(layers[ours]) for ours in names}
    for i in range(c.layers):
        for ours, (hf_name, transpose) in names.items():
            state[f"model.layers.{i}.{hf_name}"] = _torch_tensor(
                host[ours][i], transpose=transpose
            )
    torch.save(state, path / "pytorch_model.bin")
    (path / "config.json").write_text(
        json.dumps(
            {
                "architectures": ["LlamaForCausalLM"],
                "model_type": "llama",
                "vocab_size": c.vocab_size,
                "hidden_size": c.hidden,
                "num_hidden_layers": c.layers,
                "num_attention_heads": c.heads,
                "num_key_value_heads": c.kv_heads,
                "head_dim": c.head_dim,
                "intermediate_size": c.intermediate,
                "rope_theta": c.rope_theta,
                "rms_norm_eps": c.norm_eps,
                "max_position_embeddings": c.max_seq_len,
                "tie_word_embeddings": False,
                "torch_dtype": "float32",
            },
            indent=2,
        )
    )


def load_llama_checkpoint(checkpoint_dir: str, config: LlamaConfig) -> dict:
    state = _load_state_dict(Path(checkpoint_dir))
    c = config
    dt = c.dtype
    g = _getter(state)
    head = _load_head_tensors(state, g, dt)
    return {
        "embed": head["embed"],
        "layers": {
            **_load_attn_layers(g, c.layers, dt),
            "w_gate": _stack_layers(g, "layers.{i}.mlp.gate_proj.weight", c.layers, dt),
            "w_up": _stack_layers(g, "layers.{i}.mlp.up_proj.weight", c.layers, dt),
            "w_down": _stack_layers(g, "layers.{i}.mlp.down_proj.weight", c.layers, dt),
        },
        "final_norm": head["final_norm"],
        "lm_head": head["lm_head"],
    }


# ---------------------------------------------------------------------------
# Mixtral (MoE) checkpoints
# ---------------------------------------------------------------------------

# HF Mixtral layout ↔ ours: attention tensors match Llama; the FFN becomes
# block_sparse_moe — gate.weight (E, H) is the router, and each expert e has
# w1 (gate, I×H), w2 (down, H×I), w3 (up, I×H). Ours stacks them as
# w_gate/w_up (L, E, H, I) and w_down (L, E, I, H); router (L, H, E) f32.


def save_moe_checkpoint(
    params: dict, config: "MoEConfig", checkpoint_dir: str
) -> None:
    """HF-Mixtral-format writer — the inverse of :func:`load_moe_checkpoint`
    so MoE checkpoints round-trip with the HF ecosystem."""
    import json

    import torch

    path = Path(checkpoint_dir)
    path.mkdir(parents=True, exist_ok=True)
    c = config
    layers = params["layers"]

    state = _save_head_tensors(params)
    host = {ours: np.asarray(layers[ours]) for ours in _ATTN_NAMES}
    router = np.asarray(layers["router"])          # (L, H, E)
    w_gate = np.asarray(layers["w_gate"])          # (L, E, H, I)
    w_up = np.asarray(layers["w_up"])
    w_down = np.asarray(layers["w_down"])          # (L, E, I, H)
    for i in range(c.layers):
        for ours, (hf_name, transpose) in _ATTN_NAMES.items():
            state[f"model.layers.{i}.{hf_name}"] = _torch_tensor(
                host[ours][i], transpose
            )
        state[f"model.layers.{i}.block_sparse_moe.gate.weight"] = _torch_tensor(
            router[i]
        )
        for e in range(c.experts):
            base = f"model.layers.{i}.block_sparse_moe.experts.{e}"
            state[f"{base}.w1.weight"] = _torch_tensor(w_gate[i, e])
            state[f"{base}.w3.weight"] = _torch_tensor(w_up[i, e])
            state[f"{base}.w2.weight"] = _torch_tensor(w_down[i, e])
    torch.save(state, path / "pytorch_model.bin")
    (path / "config.json").write_text(
        json.dumps(
            {
                "architectures": ["MixtralForCausalLM"],
                "model_type": "mixtral",
                "vocab_size": c.vocab_size,
                "hidden_size": c.hidden,
                "num_hidden_layers": c.layers,
                "num_attention_heads": c.heads,
                "num_key_value_heads": c.kv_heads,
                "head_dim": c.head_dim,
                "intermediate_size": c.moe_intermediate,
                "num_local_experts": c.experts,
                "num_experts_per_tok": c.experts_per_token,
                "rope_theta": c.rope_theta,
                "rms_norm_eps": c.norm_eps,
                "max_position_embeddings": c.max_seq_len,
                "tie_word_embeddings": False,
                "torch_dtype": "float32",
            },
            indent=2,
        )
    )


def load_moe_checkpoint(checkpoint_dir: str, config: "MoEConfig") -> dict:
    state = _load_state_dict(Path(checkpoint_dir))
    c = config
    dt = c.dtype
    g = _getter(state)
    head = _load_head_tensors(state, g, dt)

    def stack_experts(w: str) -> jnp.ndarray:
        # (L, E, in, out): HF stores each expert as (out, in). Cast each
        # expert matrix to the model dtype as it is read — stacking a full
        # mixtral-8x7b expert tensor in f32 first would add ~60 GB of peak
        # host memory per projection.
        return jnp.stack(
            [
                jnp.stack(
                    [
                        jnp.asarray(
                            g(
                                f"layers.{i}.block_sparse_moe.experts.{e}.{w}.weight"
                            ).T,
                            dtype=dt,
                        )
                        for e in range(c.experts)
                    ]
                )
                for i in range(c.layers)
            ]
        )

    return {
        "embed": head["embed"],
        "layers": {
            **_load_attn_layers(g, c.layers, dt),
            # router stays float32 (routing decisions are numerically
            # delicate — matches init_moe_params)
            "router": jnp.asarray(
                np.stack(
                    [
                        g(f"layers.{i}.block_sparse_moe.gate.weight").T
                        for i in range(c.layers)
                    ]
                ),
                dtype=jnp.float32,
            ),
            "w_gate": stack_experts("w1"),
            "w_up": stack_experts("w3"),
            "w_down": stack_experts("w2"),
        },
        "final_norm": head["final_norm"],
        "lm_head": head["lm_head"],
    }
