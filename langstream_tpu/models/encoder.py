"""BERT/MiniLM-class text encoder for embeddings, pure JAX.

This is the in-tree engine behind ``compute-ai-embeddings`` (the reference
calls OpenAI/HF embedding APIs; ``ComputeAIEmbeddingsStep.java:46``).
Architecture matches sentence-transformers all-MiniLM-L6-v2 (6 layers, 384
hidden, 12 heads, GELU, post-LN) with mean pooling + L2 normalisation, so
real checkpoints can be loaded when weight files are present
(:func:`load_from_sentence_transformers`); random init otherwise (tests,
offline dev).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 30522
    hidden: int = 384
    layers: int = 6
    heads: int = 12
    intermediate: int = 1536
    max_position: int = 512
    norm_eps: float = 1e-12
    dtype: Any = jnp.float32

    @classmethod
    def minilm_l6(cls) -> "EncoderConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "EncoderConfig":
        # vocab covers the byte tokenizer (256 bytes + specials)
        return cls(vocab_size=384, hidden=32, layers=2, heads=4,
                   intermediate=64, max_position=64)


def init_encoder_params(config: EncoderConfig, key: jax.Array | None = None) -> dict:
    key = key if key is not None else jax.random.PRNGKey(0)
    c = config
    ks = jax.random.split(key, 12)

    def w(k, *shape, fan_in):
        return (
            jax.random.normal(k, shape, dtype=jnp.float32) / math.sqrt(fan_in)
        ).astype(c.dtype)

    L = c.layers
    return {
        "tok_embed": w(ks[0], c.vocab_size, c.hidden, fan_in=c.hidden),
        "pos_embed": w(ks[1], c.max_position, c.hidden, fan_in=c.hidden),
        "embed_norm_w": jnp.ones((c.hidden,), c.dtype),
        "embed_norm_b": jnp.zeros((c.hidden,), c.dtype),
        "layers": {
            "wq": w(ks[2], L, c.hidden, c.hidden, fan_in=c.hidden),
            "bq": jnp.zeros((L, c.hidden), c.dtype),
            "wk": w(ks[3], L, c.hidden, c.hidden, fan_in=c.hidden),
            "bk": jnp.zeros((L, c.hidden), c.dtype),
            "wv": w(ks[4], L, c.hidden, c.hidden, fan_in=c.hidden),
            "bv": jnp.zeros((L, c.hidden), c.dtype),
            "wo": w(ks[5], L, c.hidden, c.hidden, fan_in=c.hidden),
            "bo": jnp.zeros((L, c.hidden), c.dtype),
            "attn_norm_w": jnp.ones((L, c.hidden), c.dtype),
            "attn_norm_b": jnp.zeros((L, c.hidden), c.dtype),
            "w1": w(ks[6], L, c.hidden, c.intermediate, fan_in=c.hidden),
            "b1": jnp.zeros((L, c.intermediate), c.dtype),
            "w2": w(ks[7], L, c.intermediate, c.hidden, fan_in=c.intermediate),
            "b2": jnp.zeros((L, c.hidden), c.dtype),
            "mlp_norm_w": jnp.ones((L, c.hidden), c.dtype),
            "mlp_norm_b": jnp.zeros((L, c.hidden), c.dtype),
        },
    }


def encoder_param_specs(config: EncoderConfig) -> dict:
    """TP specs (column/row split per layer); dp shards the batch."""
    return {
        "tok_embed": P(None, None),
        "pos_embed": P(None, None),
        "embed_norm_w": P(None),
        "embed_norm_b": P(None),
        "layers": {
            "wq": P(None, None, "tp"), "bq": P(None, "tp"),
            "wk": P(None, None, "tp"), "bk": P(None, "tp"),
            "wv": P(None, None, "tp"), "bv": P(None, "tp"),
            "wo": P(None, "tp", None), "bo": P(None, None),
            "attn_norm_w": P(None, None), "attn_norm_b": P(None, None),
            "w1": P(None, None, "tp"), "b1": P(None, "tp"),
            "w2": P(None, "tp", None), "b2": P(None, None),
            "mlp_norm_w": P(None, None), "mlp_norm_b": P(None, None),
        },
    }


def _layer_norm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=-1, keepdims=True)
    return (((xf - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype)) * w + b


def encode(
    config: EncoderConfig,
    params: dict,
    tokens: jax.Array,   # (B, S) int32, right-padded
    mask: jax.Array,     # (B, S) 1 for real tokens
) -> jax.Array:
    """→ (B, hidden) L2-normalised sentence embeddings (mean pooling)."""
    c = config
    B, S = tokens.shape
    x = jnp.take(params["tok_embed"], tokens, axis=0)
    x = x + params["pos_embed"][None, :S]
    x = _layer_norm(x, params["embed_norm_w"], params["embed_norm_b"], c.norm_eps)
    attn_mask = (mask[:, None, None, :] == 1)
    neg = jnp.finfo(jnp.float32).min
    head_dim = c.hidden // c.heads

    def layer(x, lp):
        q = (jnp.einsum("bsh,hd->bsd", x, lp["wq"]) + lp["bq"]).reshape(
            B, S, c.heads, head_dim
        )
        k = (jnp.einsum("bsh,hd->bsd", x, lp["wk"]) + lp["bk"]).reshape(
            B, S, c.heads, head_dim
        )
        v = (jnp.einsum("bsh,hd->bsd", x, lp["wv"]) + lp["bv"]).reshape(
            B, S, c.heads, head_dim
        )
        scores = jnp.einsum("bqnd,bknd->bnqk", q, k).astype(jnp.float32)
        scores = scores / math.sqrt(head_dim)
        scores = jnp.where(attn_mask, scores, neg)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        out = jnp.einsum("bnqk,bknd->bqnd", probs, v).reshape(B, S, c.hidden)
        out = jnp.einsum("bsd,dh->bsh", out, lp["wo"]) + lp["bo"]
        x = _layer_norm(x + out, lp["attn_norm_w"], lp["attn_norm_b"], c.norm_eps)
        h = jax.nn.gelu(jnp.einsum("bsh,hi->bsi", x, lp["w1"]) + lp["b1"])
        h = jnp.einsum("bsi,ih->bsh", h, lp["w2"]) + lp["b2"]
        x = _layer_norm(x + h, lp["mlp_norm_w"], lp["mlp_norm_b"], c.norm_eps)
        return x, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    # mean pooling over real tokens, then L2 normalise
    m = mask[..., None].astype(x.dtype)
    pooled = (x * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1.0)
    pooled = pooled.astype(jnp.float32)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)


def load_from_sentence_transformers(model_name_or_path: str) -> tuple[EncoderConfig, dict]:
    """Load real MiniLM weights when available locally (gated on weight
    files being present; no network in this environment)."""
    import numpy as np
    from pathlib import Path

    path = Path(model_name_or_path)
    if not path.exists():
        raise FileNotFoundError(
            f"no local checkpoint at {model_name_or_path}; download is not "
            f"possible offline"
        )
    import torch  # cpu-only torch is in the image

    state = torch.load(path / "pytorch_model.bin", map_location="cpu")
    c = EncoderConfig.minilm_l6()

    def get(name):
        return jnp.asarray(np.asarray(state[name]))

    layers: dict[str, list] = {k: [] for k in (
        "wq", "bq", "wk", "bk", "wv", "bv", "wo", "bo",
        "attn_norm_w", "attn_norm_b", "w1", "b1", "w2", "b2",
        "mlp_norm_w", "mlp_norm_b",
    )}
    for i in range(c.layers):
        p = f"encoder.layer.{i}."
        layers["wq"].append(get(p + "attention.self.query.weight").T)
        layers["bq"].append(get(p + "attention.self.query.bias"))
        layers["wk"].append(get(p + "attention.self.key.weight").T)
        layers["bk"].append(get(p + "attention.self.key.bias"))
        layers["wv"].append(get(p + "attention.self.value.weight").T)
        layers["bv"].append(get(p + "attention.self.value.bias"))
        layers["wo"].append(get(p + "attention.output.dense.weight").T)
        layers["bo"].append(get(p + "attention.output.dense.bias"))
        layers["attn_norm_w"].append(get(p + "attention.output.LayerNorm.weight"))
        layers["attn_norm_b"].append(get(p + "attention.output.LayerNorm.bias"))
        layers["w1"].append(get(p + "intermediate.dense.weight").T)
        layers["b1"].append(get(p + "intermediate.dense.bias"))
        layers["w2"].append(get(p + "output.dense.weight").T)
        layers["b2"].append(get(p + "output.dense.bias"))
        layers["mlp_norm_w"].append(get(p + "output.LayerNorm.weight"))
        layers["mlp_norm_b"].append(get(p + "output.LayerNorm.bias"))
    params = {
        "tok_embed": get("embeddings.word_embeddings.weight"),
        "pos_embed": get("embeddings.position_embeddings.weight"),
        "embed_norm_w": get("embeddings.LayerNorm.weight"),
        "embed_norm_b": get("embeddings.LayerNorm.bias"),
        "layers": {k: jnp.stack(v) for k, v in layers.items()},
    }
    return c, params
