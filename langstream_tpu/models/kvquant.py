"""int8 KV cache for the dense layout — the decode-bandwidth lever.

Decode throughput is bounded by HBM reads of weights + the KV window
(serving/profiling.py roofline); at serving shapes the KV window is the
larger term. Per-row absmax int8 (one f32 scale per (position, kv-head)
row) halves that traffic at ~1e-2 relative error on attention logits.

TPU-first read path — the dequantisation never materialises a bf16 cache:

- **Scores**: the scale is constant along the contracted ``head_dim``, so
  ``q . dequant(k)`` == ``(q . k_int8) * scale`` — the int8→bf16 convert
  fuses into the dot operand and the scale multiplies the (small) score
  tensor.
- **Values**: the scale varies along the contracted ``seq`` axis, so it
  folds into the (small) probability tensor instead:
  ``probs . dequant(v)`` == ``(probs * scale) . v_int8``.

Cache representation: ``{"q": int8 (L, B, S, K, D), "s": f32 (L, B, S, K)}``
— a pytree that flows through jit/scan/donation/sharding like the plain
bf16 array it replaces (engine shards "q" and "s" with the same dp/tp
axes). Write sites (prefill row fill, decode-chunk commit, single-step
write) quantise; prefill's own attention runs on the fresh bf16 K/V it
just computed, so quantisation error only enters through cross-step
cache reads.

Reference anchor: the reference has no serving engine at all (models are
SaaS HTTP calls, SURVEY §2.6) — this is net-new TPU capability on the
path of `ai-chat-completions`.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def is_quant_cache(cache: Any) -> bool:
    return isinstance(cache, dict) and "q" in cache and "s" in cache


def cache_seq_len(cache: Any) -> int:
    """Sequence-axis size of a dense cache in either layout."""
    return (cache["q"] if is_quant_cache(cache) else cache).shape[2]


def cache_slice_window(cache: Any, window: int) -> Any:
    """Static window slice over the sequence axis (axis 2 in both the
    (L,B,S,K,D) data and (L,B,S,K) scale leaves)."""
    slc = lambda a: jax.lax.slice_in_dim(a, 0, window, axis=2)
    return jax.tree.map(slc, cache) if is_quant_cache(cache) else slc(cache)


def quantize_rows(x: jax.Array) -> dict[str, jax.Array]:
    """Per-row absmax int8 over the trailing ``head_dim`` axis.

    ``x``: (..., D) bf16/f32 → {"q": int8 (..., D), "s": f32 (...,)}.
    """
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(xf / scale[..., None]), -127.0, 127.0
    ).astype(jnp.int8)
    return {"q": q, "s": scale}


def dequantize_rows(cache: dict[str, jax.Array], dtype=jnp.bfloat16) -> jax.Array:
    """Reference-path dequantisation (tests / debugging — the serving read
    path never calls this; it fuses the scales into scores/probs)."""
    return (
        cache["q"].astype(jnp.float32) * cache["s"][..., None]
    ).astype(dtype)


def init_kv_cache_int8(
    config, slots: int, max_seq_len: int | None = None
) -> tuple[dict[str, jax.Array], dict[str, jax.Array]]:
    """Zeroed int8 caches, same logical shape as :func:`init_kv_cache`."""
    c = config
    seq = max_seq_len or c.max_seq_len
    shape = (c.layers, slots, seq, c.kv_heads)
    make = lambda: {
        "q": jnp.zeros(shape + (c.head_dim,), dtype=jnp.int8),
        "s": jnp.zeros(shape, dtype=jnp.float32),
    }
    return make(), make()


def cache_write_rows(cache: Any, rows: jax.Array, index) -> Any:
    """Write bf16 ``rows`` into ``cache`` at ``index`` (an advanced-index
    tuple or slice over the leading cache axes), quantising when the cache
    is int8. Works for the plain-array cache too, so call sites stay
    layout-agnostic."""
    if not is_quant_cache(cache):
        return cache.at[index].set(rows.astype(cache.dtype))
    quant = quantize_rows(rows)
    return {
        "q": cache["q"].at[index].set(quant["q"]),
        "s": cache["s"].at[index].set(quant["s"]),
    }


def cache_scores(qg: jax.Array, ck_l: Any) -> jax.Array:
    """Attention scores of grouped queries against a cache layer slice.

    ``qg``: (B, K, G, D); ``ck_l``: (B, S, K, D) bf16 or int8 dict.
    Returns f32 (B, K, G, S) — unscaled by 1/sqrt(D) (caller applies)."""
    if not is_quant_cache(ck_l):
        return jnp.einsum("bkgd,bskd->bkgs", qg, ck_l).astype(jnp.float32)
    s = jnp.einsum(
        "bkgd,bskd->bkgs", qg, ck_l["q"].astype(qg.dtype)
    ).astype(jnp.float32)
    # scale is constant along D: factor it out of the dot
    return s * ck_l["s"].transpose(0, 2, 1)[:, :, None, :]


def cache_values(probs: jax.Array, cv_l: Any) -> jax.Array:
    """Value mix for a cache layer slice.

    ``probs``: (B, K, G, S) model dtype; ``cv_l``: (B, S, K, D) bf16 or
    int8 dict. Returns (B, K, G, D) in the probs dtype."""
    if not is_quant_cache(cv_l):
        return jnp.einsum("bkgs,bskd->bkgd", probs, cv_l)
    # scale varies along the contracted S axis: fold it into the probs
    scaled = (
        probs.astype(jnp.float32)
        * cv_l["s"].transpose(0, 2, 1)[:, :, None, :]
    ).astype(probs.dtype)
    return jnp.einsum(
        "bkgs,bskd->bkgd", scaled, cv_l["q"].astype(probs.dtype)
    )
