"""Llama-family decoder, pure JAX, TPU-first.

Design choices (vs. a torch port):
- **Stacked layer params + ``lax.scan``**: one compiled layer body instead of
  N inlined layers — faster compiles, identical runtime (XLA unrolls DMA
  pipelining itself).
- **bfloat16 weights/activations, float32 softmax+norms**: MXU-native.
- **GQA attention via grouped einsum** — no KV head replication, so the KV
  cache stays small and HBM-bandwidth-friendly.
- **Static shapes everywhere**: prefill pads to length buckets; decode is a
  fixed (slots,) batch. No data-dependent control flow inside jit.
- **TP sharding rules** (Megatron-style, over the ``tp`` mesh axis):
  attention QKV and MLP up/gate are column-sharded, attention out and MLP
  down row-sharded; XLA inserts the psums on ICI. KV cache shards on the KV
  head axis; batch (slots) shards on ``dp``.

Capability parity: this is the engine behind ``ai-chat-completions`` /
``ai-text-completions`` (reference: ``ChatCompletionsStep.java`` calling
OpenAI etc. — here the model is local).
"""

from __future__ import annotations

import dataclasses
import math
import os
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from langstream_tpu.models.kvquant import (
    cache_scores,
    cache_seq_len,
    cache_slice_window,
    cache_values,
    cache_write_rows,
    is_quant_cache,
    quantize_rows,
)
from langstream_tpu.models.quant import as_weight as _w, embedding_take


def _flash_mode(seq_len: int) -> str | None:
    """Whether prefill attention should use the Pallas flash kernel.

    ``LS_TPU_FLASH``: ``auto`` (default — compiled kernel on TPU for
    long-enough sequences), ``1``/``0`` force on/off, ``interpret`` runs the
    kernel in interpreter mode (CPU tests).
    """
    env = os.environ.get("LS_TPU_FLASH", "auto").lower()
    if env == "interpret":
        return "interpret"
    if env in ("1", "true", "on"):
        return "compiled"
    if env in ("0", "false", "off"):
        return None
    return (
        "compiled"
        if jax.default_backend() == "tpu" and seq_len >= 512
        else None
    )


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden: int = 2048
    layers: int = 16
    heads: int = 16
    kv_heads: int = 8
    head_dim: int = 128
    intermediate: int = 5632
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16

    @classmethod
    def llama3_8b(cls, max_seq_len: int = 8192) -> "LlamaConfig":
        return cls(
            vocab_size=128256, hidden=4096, layers=32, heads=32, kv_heads=8,
            head_dim=128, intermediate=14336, rope_theta=500000.0,
            max_seq_len=max_seq_len,
        )

    @classmethod
    def llama3_70b(cls, max_seq_len: int = 8192) -> "LlamaConfig":
        return cls(
            vocab_size=128256, hidden=8192, layers=80, heads=64, kv_heads=8,
            head_dim=128, intermediate=28672, rope_theta=500000.0,
            max_seq_len=max_seq_len,
        )

    @classmethod
    def llama_1b(cls, max_seq_len: int = 2048) -> "LlamaConfig":
        """~1.2B params — the per-chip share of Llama-3-8B under TP8, used as
        the single-chip benchmark proxy (BASELINE.md config #2/#5)."""
        return cls(
            vocab_size=32000, hidden=2048, layers=16, heads=16, kv_heads=8,
            head_dim=128, intermediate=5632, max_seq_len=max_seq_len,
        )

    @classmethod
    def tiny(cls, max_seq_len: int = 128) -> "LlamaConfig":
        """Test-size config (CPU-mesh tests, dry runs). Vocab covers the
        byte-level tokenizer (256 bytes + specials)."""
        return cls(
            vocab_size=384, hidden=64, layers=2, heads=4, kv_heads=2,
            head_dim=16, intermediate=128, max_seq_len=max_seq_len,
        )


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------


def init_llama_params(config: LlamaConfig, key: jax.Array | None = None) -> dict:
    """Random-init params (stacked per-layer leading dim L)."""
    key = key if key is not None else jax.random.PRNGKey(0)
    c = config
    keys = jax.random.split(key, 10)
    qkv_dim = c.heads * c.head_dim
    kv_dim = c.kv_heads * c.head_dim

    def norm_init(*shape):
        return jnp.ones(shape, dtype=c.dtype)

    def w_init(k, *shape, fan_in):
        scale = 1.0 / math.sqrt(fan_in)
        return (jax.random.normal(k, shape, dtype=jnp.float32) * scale).astype(c.dtype)

    L = c.layers
    return {
        "embed": w_init(keys[0], c.vocab_size, c.hidden, fan_in=c.hidden),
        "layers": {
            "attn_norm": norm_init(L, c.hidden),
            "wq": w_init(keys[1], L, c.hidden, qkv_dim, fan_in=c.hidden),
            "wk": w_init(keys[2], L, c.hidden, kv_dim, fan_in=c.hidden),
            "wv": w_init(keys[3], L, c.hidden, kv_dim, fan_in=c.hidden),
            "wo": w_init(keys[4], L, qkv_dim, c.hidden, fan_in=qkv_dim),
            "mlp_norm": norm_init(L, c.hidden),
            "w_gate": w_init(keys[5], L, c.hidden, c.intermediate, fan_in=c.hidden),
            "w_up": w_init(keys[6], L, c.hidden, c.intermediate, fan_in=c.hidden),
            "w_down": w_init(keys[7], L, c.intermediate, c.hidden, fan_in=c.intermediate),
        },
        "final_norm": norm_init(c.hidden),
        "lm_head": w_init(keys[8], c.hidden, c.vocab_size, fan_in=c.hidden),
    }


def llama_param_specs(config: LlamaConfig) -> dict:
    """PartitionSpecs per param (Megatron TP over axis ``tp``)."""
    return {
        "embed": P("tp", None),          # vocab-sharded
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "tp"),   # column (heads)
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),   # row
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, "tp"),
            "w_up": P(None, None, "tp"),
            "w_down": P(None, "tp", None),
        },
        "final_norm": P(None),
        "lm_head": P(None, "tp"),        # vocab-sharded logits
    }


def shard_llama_params(params: dict, config: LlamaConfig, mesh: Mesh) -> dict:
    specs = llama_param_specs(config)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def kv_cache_spec(mesh_axes: tuple[str, ...]) -> P:
    """Cache (L, slots, S, kv_heads, head_dim): slots on dp, kv heads on tp."""
    dp = "dp" if "dp" in mesh_axes else None
    tp = "tp" if "tp" in mesh_axes else None
    return P(None, dp, None, tp, None)


def init_kv_cache(
    config: LlamaConfig, slots: int, max_seq_len: int | None = None
) -> tuple[jax.Array, jax.Array]:
    c = config
    seq = max_seq_len or c.max_seq_len
    shape = (c.layers, slots, seq, c.kv_heads, c.head_dim)
    return jnp.zeros(shape, dtype=c.dtype), jnp.zeros(shape, dtype=c.dtype)


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------


def _rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * w


def _rope(positions: jax.Array, head_dim: int, theta: float) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for the given positions: (..., head_dim//2)."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs
    return jnp.cos(angles), jnp.sin(angles)


def _apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (..., heads, head_dim); cos/sin broadcast over the heads axis."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(
        x.dtype
    )


def _swiglu(x, w_gate, w_up, w_down):
    gate = jax.nn.silu(jnp.einsum("...h,hi->...i", x, _w(w_gate)))
    up = jnp.einsum("...h,hi->...i", x, _w(w_up))
    return jnp.einsum("...i,ih->...h", gate * up, _w(w_down))


def _default_ffn(h, lp, valid=None):
    """The dense SwiGLU FFN sub-block. ``ffn`` hooks on the forward/prefill/
    decode entry points default to this; the MoE family swaps in its routed
    expert FFN (models/moe.py) and reuses every attention/cache path here.
    ``valid`` marks real positions — pointwise FFNs ignore it, routed ones
    must not let pad/inactive positions consume expert capacity."""
    return _swiglu(h, lp["w_gate"], lp["w_up"], lp["w_down"])


def attention_block(config, x, lp, cos, sin, attention):
    """Pre-norm attention sub-block + residual: the piece shared verbatim by
    the dense, MoE, and pipeline-stage forwards (they differ only in FFN and
    sharding hooks). ``config`` needs heads/kv_heads/head_dim/norm_eps — both
    LlamaConfig and MoEConfig qualify."""
    c = config
    B, S = x.shape[0], x.shape[1]
    h = _rms_norm(x, lp["attn_norm"], c.norm_eps)
    q = jnp.einsum("bph,hd->bpd", h, _w(lp["wq"])).reshape(B, S, c.heads, c.head_dim)
    k = jnp.einsum("bph,hd->bpd", h, _w(lp["wk"])).reshape(B, S, c.kv_heads, c.head_dim)
    v = jnp.einsum("bph,hd->bpd", h, _w(lp["wv"])).reshape(B, S, c.kv_heads, c.head_dim)
    q = _apply_rope(q, cos, sin)
    k = _apply_rope(k, cos, sin)
    out = attention(q, k, v).reshape(B, S, c.heads * c.head_dim)
    return x + jnp.einsum("bpd,dh->bph", out, _w(lp["wo"]))


# ---------------------------------------------------------------------------
# batched ragged LoRA (Punica/S-LoRA-style adapter gather)
# ---------------------------------------------------------------------------


def lora_delta(h: jax.Array, ids: jax.Array, a: jax.Array, b: jax.Array):
    """Per-slot low-rank delta ``h @ A[id] @ B[id]`` for one projection.

    ``a``/``b`` are one layer's slices of the stacked adapter buffers —
    ``(n_rows, d_in, rank)`` / ``(n_rows, rank, d_out)`` — and ``ids``
    is the per-slot ``(B,)`` int32 row index. Row 0 is all-zeros, so
    adapter-less slots compute the base model exactly; heterogeneous-
    adapter batches stay ONE jitted program (the gather is data, not
    structure — no per-adapter recompiles). The LoRA alpha/rank scale
    is folded into B at publish time (serving/adapters.py)."""
    a_sel = jnp.take(a, ids, axis=0)  # (B, d_in, rank)
    b_sel = jnp.take(b, ids, axis=0)  # (B, rank, d_out)
    if h.ndim == 2:
        t = jnp.einsum("bh,bhr->br", h, a_sel)
        return jnp.einsum("br,bro->bo", t, b_sel)
    t = jnp.einsum("bph,bhr->bpr", h, a_sel)
    return jnp.einsum("bpr,bro->bpo", t, b_sel)


# ---------------------------------------------------------------------------
# prefill
# ---------------------------------------------------------------------------


def prefill_forward(
    config: LlamaConfig,
    params: dict,
    tokens: jax.Array,       # (B, P) int32, right-padded
    lengths: jax.Array,      # (B,) true lengths
    use_flash: bool | None = None,
    mesh: Mesh | None = None,  # flash under a mesh runs via shard_map
    ffn=None,                # (h (B,P,H), lp, valid=None) -> (B,P,H);
                             # default dense SwiGLU
    adapters: dict | None = None,  # {"ids": (B,) int32, "layers":
                             # {wq_a (L,N,H,r), wq_b (L,N,r,qd), ...}} —
                             # None keeps the seed jaxpr untouched
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Shared prompt forward (the single source of the prefill layer math):
    returns (last-token logits (B,V), ks, vs) where ks/vs are the roped
    per-layer K/V ``(L, B, P, Kh, D)`` for the caller's cache layout —
    dense (:func:`llama_prefill`) or paged (``llama_prefill_paged``)."""
    c = config
    if ffn is None:
        ffn = _default_ffn
    B, Pn = tokens.shape
    x = embedding_take(params["embed"], tokens)  # (B, P, H)
    positions = jnp.arange(Pn)[None, :].repeat(B, axis=0)
    cos, sin = _rope(positions, c.head_dim, c.rope_theta)
    # causal + padding mask: (B, 1, P, P)
    q_idx = jnp.arange(Pn)[:, None]
    k_idx = jnp.arange(Pn)[None, :]
    causal = q_idx >= k_idx
    valid = k_idx < lengths[:, None, None]  # (B, 1, P) keys within length
    mask = causal[None, :, :] & valid
    # (B, P) real-token mask for the FFN hook: routed (MoE) FFNs must not
    # let right-padding consume expert capacity
    pos_valid = jnp.arange(Pn)[None, :] < lengths[:, None]
    neg = jnp.finfo(jnp.float32).min

    flash = _flash_mode(Pn) if use_flash is None else ("compiled" if use_flash else None)

    # Sequence-parallel prefill: with an ``sp`` axis in the mesh the prompt's
    # sequence dimension shards over it and attention runs as a ring
    # collective (ppermute K/V rotation + online softmax, parallel/ring.py).
    # This is the long-context serving path: prefill FLOPs and activation
    # memory split ~sp-ways (the KV cache itself stays in the engine's
    # dp/tp layout — decode is unchanged). Takes priority over the Pallas
    # flash kernel, which keeps the sequence resident per device.
    sp_ring = (
        mesh is not None
        and "sp" in mesh.axis_names
        and mesh.shape["sp"] > 1
        and Pn % mesh.shape["sp"] == 0
    )
    if sp_ring:
        # degrade per-axis like the flash path: a batch that doesn't divide
        # dp (e.g. one queued request on a dp>1 mesh) replicates over dp
        # instead of crashing the prefill; heads that don't divide tp stay
        # unsharded in the ring
        sp_dp = (
            "dp"
            if "dp" in mesh.axis_names and B % mesh.shape["dp"] == 0
            else None
        )
        sp_tp = (
            "tp"
            if "tp" in mesh.axis_names
            and c.kv_heads % mesh.shape["tp"] == 0
            and c.heads % mesh.shape["tp"] == 0
            else None
        )
        x_spec = NamedSharding(mesh, P(sp_dp, "sp", None))
        x = jax.lax.with_sharding_constraint(x, x_spec)

    def layer(carry, layer_in):
        x = carry
        if adapters is None:
            lp = layer_in
        else:
            lp, al = layer_in
        h = _rms_norm(x, lp["attn_norm"], c.norm_eps)
        q = jnp.einsum("bph,hd->bpd", h, _w(lp["wq"]))
        k = jnp.einsum("bph,hd->bpd", h, _w(lp["wk"]))
        v = jnp.einsum("bph,hd->bpd", h, _w(lp["wv"]))
        if adapters is not None:
            ids = adapters["ids"]
            q = q + lora_delta(h, ids, al["wq_a"], al["wq_b"])
            k = k + lora_delta(h, ids, al["wk_a"], al["wk_b"])
            v = v + lora_delta(h, ids, al["wv_a"], al["wv_b"])
        q = q.reshape(B, Pn, c.heads, c.head_dim)
        k = k.reshape(B, Pn, c.kv_heads, c.head_dim)
        v = v.reshape(B, Pn, c.kv_heads, c.head_dim)
        q = _apply_rope(q, cos, sin)
        k = _apply_rope(k, cos, sin)
        if sp_ring:
            # causality alone hides right-padded keys from every real query
            # row (padded rows sit after all real rows); their outputs are
            # garbage the caller discards, their cache rows are overwritten
            # before ever being attended to (same argument as flash below)
            from langstream_tpu.parallel.ring import ring_attention

            out = ring_attention(
                q, k, v, mesh, causal=True,
                batch_axis=sp_dp, head_axis=sp_tp,
            )
            out = out.reshape(B, Pn, c.heads * c.head_dim)
        elif flash is not None:
            # Pallas blocked attention: no (B,H,P,P) score matrix in HBM.
            # Causality alone hides right-padded keys from every real query
            # row; padded rows' outputs are garbage the caller discards.
            from langstream_tpu.ops.flash_attention import flash_attention

            out = flash_attention(
                q, k, v, causal=True, interpret=(flash == "interpret"),
                mesh=mesh,
            )
            out = out.reshape(B, Pn, c.heads * c.head_dim)
        else:
            # grouped-query attention: heads = kv_heads * group
            G = c.heads // c.kv_heads
            qg = q.reshape(B, Pn, c.kv_heads, G, c.head_dim)
            scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k).astype(jnp.float32)
            scores = scores / math.sqrt(c.head_dim)
            scores = jnp.where(mask[:, None, None, :, :], scores, neg)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v)
            out = out.reshape(B, Pn, c.heads * c.head_dim)
        attn = jnp.einsum("bpd,dh->bph", out, _w(lp["wo"]))
        if adapters is not None:
            attn = attn + lora_delta(out, adapters["ids"], al["wo_a"], al["wo_b"])
        x = x + attn
        h2 = _rms_norm(x, lp["mlp_norm"], c.norm_eps)
        x = x + ffn(h2, lp, pos_valid)
        if sp_ring:
            x = jax.lax.with_sharding_constraint(x, x_spec)
        return x, (k, v)

    layer_xs = (
        params["layers"]
        if adapters is None
        else (params["layers"], adapters["layers"])
    )
    x, (ks, vs) = jax.lax.scan(layer, x, layer_xs)
    x = _rms_norm(x, params["final_norm"], c.norm_eps)
    # logits for the last real token of each prompt
    last = jnp.take_along_axis(
        x, (lengths - 1)[:, None, None].clip(0), axis=1
    ).squeeze(1)
    logits = jnp.einsum("bh,hv->bv", last, _w(params["lm_head"])).astype(jnp.float32)
    return logits, ks, vs


def llama_prefill(
    config: LlamaConfig,
    params: dict,
    tokens: jax.Array,       # (B, P) int32, right-padded
    lengths: jax.Array,      # (B,) true lengths
    cache_k: jax.Array,      # (L, slots, S, K, D)
    cache_v: jax.Array,
    slot_ids: jax.Array,     # (B,) which cache slots to fill
    use_flash: bool | None = None,  # None = auto (LS_TPU_FLASH)
    mesh: Mesh | None = None,  # kernel runs per-shard via shard_map
    ffn=None,                # pluggable FFN sub-block (MoE family hook)
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Process prompts, fill the KV cache, return last-token logits (B, V).

    Only the first P rows of each slot are written; stale rows beyond are
    harmless — every decode read is masked to positions < length, and each
    new row is written before it is ever attended to.
    """
    Pn = tokens.shape[1]
    logits, ks, vs = prefill_forward(
        config, params, tokens, lengths, use_flash, mesh=mesh, ffn=ffn
    )
    idx = (slice(None), slot_ids, slice(None, Pn))
    new_k = cache_write_rows(cache_k, ks, idx)
    new_v = cache_write_rows(cache_v, vs, idx)
    return logits, new_k, new_v


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------


def llama_decode_step(
    config: LlamaConfig,
    params: dict,
    tokens: jax.Array,     # (B,) current token per slot
    lengths: jax.Array,    # (B,) tokens already in cache per slot
    cache_k: jax.Array,    # (L, B, S, K, D)
    cache_v: jax.Array,
    ffn=None,              # (h (B,H), lp, valid=None) -> (B,H); default SwiGLU
    active: jax.Array | None = None,  # (B,) bool — forwarded to the FFN hook
                                      # so routed (MoE) FFNs don't let dead
                                      # slots consume expert capacity
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for every slot; returns logits (B, V) + new caches.

    The new K/V is written at position ``lengths`` per slot; attention spans
    positions 0..lengths inclusive. Inactive slots produce garbage logits
    the engine ignores (no dynamic shapes) — but with a routed FFN pass
    ``active`` too, or dead slots' garbage competes for expert capacity.
    """
    c = config
    if ffn is None:
        ffn = _default_ffn
    if active is None:
        active = jnp.ones(tokens.shape[0], dtype=bool)
    B = tokens.shape[0]
    S = cache_seq_len(cache_k)
    x = embedding_take(params["embed"], tokens)  # (B, H)
    cos, sin = _rope(lengths, c.head_dim, c.rope_theta)  # (B, half)
    k_idx = jnp.arange(S)[None, :]
    key_mask = k_idx <= lengths[:, None]  # (B, S)
    neg = jnp.finfo(jnp.float32).min
    G = c.heads // c.kv_heads
    batch_idx = jnp.arange(B)

    def layer(carry, layer_in):
        x = carry
        lp, ck_l, cv_l = layer_in
        h = _rms_norm(x, lp["attn_norm"], c.norm_eps)
        q = (h @ _w(lp["wq"])).reshape(B, c.heads, c.head_dim)
        k = (h @ _w(lp["wk"])).reshape(B, c.kv_heads, c.head_dim)
        v = (h @ _w(lp["wv"])).reshape(B, c.kv_heads, c.head_dim)
        q = _apply_rope(q, cos, sin)
        k = _apply_rope(k, cos, sin)
        ck_l = cache_write_rows(ck_l, k, (batch_idx, lengths))
        cv_l = cache_write_rows(cv_l, v, (batch_idx, lengths))
        qg = q.reshape(B, c.kv_heads, G, c.head_dim)
        scores = cache_scores(qg, ck_l) / math.sqrt(c.head_dim)
        scores = jnp.where(key_mask[:, None, None, :], scores, neg)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        out = cache_values(probs, cv_l)
        out = out.reshape(B, c.heads * c.head_dim)
        x = x + out @ _w(lp["wo"])
        h2 = _rms_norm(x, lp["mlp_norm"], c.norm_eps)
        x = x + ffn(h2, lp, active)
        return x, (ck_l, cv_l)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], cache_k, cache_v)
    )
    x = _rms_norm(x, params["final_norm"], c.norm_eps)
    logits = (x @ _w(params["lm_head"])).astype(jnp.float32)
    return logits, new_k, new_v


def llama_decode_chunk(
    config: LlamaConfig,
    params: dict,
    tokens0: jax.Array,       # (B,) current token per slot
    base_lengths: jax.Array,  # (B,) tokens in cache at chunk start
    active: jax.Array,        # (B,) bool
    cache_k: jax.Array,       # (L, B, S, K, D) — READ-ONLY during the chunk
    cache_v: jax.Array,
    sample_fn,                # (logits, key) -> (tokens, logprobs)
    key: jax.Array,
    num_steps: int,
    window: int | None = None,  # static attention window: read only cache
                                # rows [0, window) — the host picks the
                                # smallest bucket covering max(base_lengths),
                                # so short sequences don't pay full-S HBM
                                # traffic (decode is cache-read bound)
    ffn=None,                   # (h (B,H), lp, valid=None) -> (B,H);
                                # default dense SwiGLU
    sample_extras=None,         # (presences, frequencies, counts0 (B, V)):
                                # penalty sampling — counts ride the step
                                # carry (each sampled token updates them);
                                # sample_fn is then called (logits, key,
                                # counts). None = plain (logits, key).
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """K fused decode steps with a two-segment KV layout.

    HBM discipline: the big cache is consumed read-only (no per-step
    rematerialisation); each step's new K/V lands in a small chunk buffer
    ``(L, B, num_steps, Kh, D)`` carried through the step scan; a single
    commit writes the buffer back into the cache at the end. Attention spans
    [cache rows < base_len] ∪ [buffer rows ≤ step]. Per-step HBM traffic is
    params + cache *read* only — the difference between ~1k and ~10k tok/s.

    Returns (chunk_tokens (K,B), chunk_logprobs (K,B), final_tokens,
    final_lengths, cache_k, cache_v) with the buffer committed.
    """
    c = config
    if ffn is None:
        ffn = _default_ffn
    B = tokens0.shape[0]
    full_k, full_v = cache_k, cache_v
    if window is not None and window < cache_seq_len(cache_k):
        # static slice: XLA reads only these rows; the commit below still
        # targets the full cache (valid because base_lengths < window)
        cache_k = cache_slice_window(cache_k, window)
        cache_v = cache_slice_window(cache_v, window)
    S = cache_seq_len(cache_k)
    G = c.heads // c.kv_heads
    adv = active.astype(jnp.int32)
    neg = jnp.finfo(jnp.float32).min
    cache_mask = (jnp.arange(S)[None, :] < base_lengths[:, None])  # (B, S) static per chunk
    kbuf0 = jnp.zeros((c.layers, B, num_steps, c.kv_heads, c.head_dim), c.dtype)
    vbuf0 = jnp.zeros_like(kbuf0)
    pen = sample_extras is not None
    counts0 = sample_extras[2] if pen else None

    def step(carry, step_idx):
        if pen:
            tokens, kbuf, vbuf, key, counts = carry
        else:
            tokens, kbuf, vbuf, key = carry
            counts = None
        key, sub = jax.random.split(key)
        x = embedding_take(params["embed"], tokens)  # (B, H)
        positions = base_lengths + step_idx * adv
        cos, sin = _rope(positions, c.head_dim, c.rope_theta)
        buf_mask = (jnp.arange(num_steps)[None, :] <= step_idx)  # (1, K)

        def layer(x, layer_in):
            lp, ck_l, cv_l, kbuf_l, vbuf_l = layer_in
            h = _rms_norm(x, lp["attn_norm"], c.norm_eps)
            q = (h @ _w(lp["wq"])).reshape(B, c.heads, c.head_dim)
            k = (h @ _w(lp["wk"])).reshape(B, c.kv_heads, c.head_dim)
            v = (h @ _w(lp["wv"])).reshape(B, c.kv_heads, c.head_dim)
            q = _apply_rope(q, cos, sin)
            k = _apply_rope(k, cos, sin)
            kbuf_l = jax.lax.dynamic_update_slice_in_dim(
                kbuf_l, k[:, None], step_idx, axis=1
            )
            vbuf_l = jax.lax.dynamic_update_slice_in_dim(
                vbuf_l, v[:, None], step_idx, axis=1
            )
            qg = q.reshape(B, c.kv_heads, G, c.head_dim)
            s_cache = cache_scores(qg, ck_l)
            s_buf = jnp.einsum("bkgd,btkd->bkgt", qg, kbuf_l).astype(jnp.float32)
            scale = 1.0 / math.sqrt(c.head_dim)
            s_cache = jnp.where(
                cache_mask[:, None, None, :], s_cache * scale, neg
            )
            s_buf = jnp.where(buf_mask[:, None, None, :], s_buf * scale, neg)
            s_all = jnp.concatenate([s_cache, s_buf], axis=-1)
            probs = jax.nn.softmax(s_all, axis=-1).astype(x.dtype)
            p_cache, p_buf = probs[..., :S], probs[..., S:]
            out = cache_values(p_cache, cv_l) + jnp.einsum(
                "bkgt,btkd->bkgd", p_buf, vbuf_l
            )
            out = out.reshape(B, c.heads * c.head_dim)
            x = x + out @ _w(lp["wo"])
            h2 = _rms_norm(x, lp["mlp_norm"], c.norm_eps)
            x = x + ffn(h2, lp, active)
            return x, (kbuf_l, vbuf_l)

        x, (kbuf, vbuf) = jax.lax.scan(
            layer, x, (params["layers"], cache_k, cache_v, kbuf, vbuf)
        )
        x = _rms_norm(x, params["final_norm"], c.norm_eps)
        logits = (x @ _w(params["lm_head"])).astype(jnp.float32)
        if pen:
            nxt, lp = sample_fn(logits, sub, counts)
        else:
            nxt, lp = sample_fn(logits, sub)
        nxt = jnp.where(active, nxt, tokens)
        if pen:
            counts = counts.at[jnp.arange(B), nxt].add(adv)
            return (nxt, kbuf, vbuf, key, counts), (nxt, lp)
        return (nxt, kbuf, vbuf, key), (nxt, lp)

    carry0 = (
        (tokens0, kbuf0, vbuf0, key, counts0)
        if pen
        else (tokens0, kbuf0, vbuf0, key)
    )
    out_carry, (chunk_tokens, chunk_lps) = jax.lax.scan(
        step, carry0, jnp.arange(num_steps)
    )
    final_tokens, kbuf, vbuf = out_carry[0], out_carry[1], out_carry[2]

    # commit: one write of the chunk buffer into the cache per slot. The
    # buffer stays bf16 through the scan (it is tiny and re-read every
    # step); an int8 cache quantises it once here.
    def commit_leaf(full_leaf, buf_leaf):
        def commit_lb(c_lb, b_lb, start):  # (S, ...), (num_steps, ...)
            return jax.lax.dynamic_update_slice(
                c_lb, b_lb, (start,) + (0,) * (c_lb.ndim - 1)
            )

        f = jax.vmap(  # over layers
            jax.vmap(commit_lb, in_axes=(0, 0, 0)), in_axes=(0, 0, None)
        )
        return f(full_leaf, buf_leaf, base_lengths)

    if is_quant_cache(full_k):
        out_k = jax.tree.map(commit_leaf, full_k, quantize_rows(kbuf))
        out_v = jax.tree.map(commit_leaf, full_v, quantize_rows(vbuf))
    else:
        out_k = commit_leaf(full_k, kbuf)
        out_v = commit_leaf(full_v, vbuf)
    final_lengths = base_lengths + num_steps * adv
    return chunk_tokens, chunk_lps, final_tokens, final_lengths, out_k, out_v


def llama_forward(
    config: LlamaConfig,
    params: dict,
    tokens: jax.Array,  # (B, S) int32
    *,
    attention=None,   # (q (B,S,H,D), k, v (B,S,Kh,D)) -> (B,S,H,D); default
                      # dense causal GQA — callers swap in ring/Ulysses
    constrain=None,   # applied to activations after embed and each layer
) -> jax.Array:
    """All-position logits (B, S, V), no KV cache — the training-side
    forward (next-token loss) and the long-context prefill building block.

    One transformer body serves the dense and the sequence-parallel paths:
    they differ only in the ``attention`` callback and the activation
    ``constrain`` hook (see :func:`llama_forward_sp`).
    """
    c = config
    B, S = tokens.shape
    if attention is None:
        from langstream_tpu.parallel.ring import dense_attention

        attention = partial(
            dense_attention, causal=True, scale=1.0 / math.sqrt(c.head_dim)
        )
    if constrain is None:
        constrain = lambda x: x  # noqa: E731
    x = constrain(embedding_take(params["embed"], tokens))
    positions = jnp.arange(S)[None, :].repeat(B, axis=0)
    cos, sin = _rope(positions, c.head_dim, c.rope_theta)

    def layer(x, lp):
        x = attention_block(c, x, lp, cos, sin, attention)
        h2 = _rms_norm(x, lp["mlp_norm"], c.norm_eps)
        x = x + _swiglu(h2, lp["w_gate"], lp["w_up"], lp["w_down"])
        return constrain(x), None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    x = _rms_norm(x, params["final_norm"], c.norm_eps)
    return jnp.einsum("bsh,hv->bsv", x, _w(params["lm_head"])).astype(jnp.float32)


def llama_forward_sp(
    config: LlamaConfig,
    params: dict,
    tokens: jax.Array,  # (B, S) int32, S divisible by the sp axis size
    mesh: Mesh,
    attn: str = "ring",
) -> jax.Array:
    """Sequence-parallel long-context forward: activations sharded on the
    ``sp`` mesh axis end to end; attention runs as a collective over ICI —
    ring attention (``ppermute`` K/V rotation + online softmax) or Ulysses
    (all-to-all head re-sharding). See :mod:`langstream_tpu.parallel.ring`.

    This is the context-parallel path for sequences that exceed one chip's
    HBM: per-device activation memory is ``S/sp``, and the full ``S×S``
    score matrix never materialises.
    """
    from langstream_tpu.parallel.ring import ring_attention, ulysses_attention

    attn_fn = {"ring": ring_attention, "ulysses": ulysses_attention}[attn]
    kwargs = {} if attn == "ulysses" else {"head_axis": "tp"}
    x_spec = NamedSharding(
        mesh, P("dp" if "dp" in mesh.axis_names else None, "sp", None)
    )
    return llama_forward(
        config, params, tokens,
        attention=lambda q, k, v: attn_fn(q, k, v, mesh, causal=True, **kwargs),
        constrain=lambda x: jax.lax.with_sharding_constraint(x, x_spec),
    )


def param_count(config: LlamaConfig) -> int:
    c = config
    per_layer = (
        c.hidden * c.heads * c.head_dim
        + 2 * c.hidden * c.kv_heads * c.head_dim
        + c.heads * c.head_dim * c.hidden
        + 3 * c.hidden * c.intermediate
        + 2 * c.hidden
    )
    return c.layers * per_layer + 2 * c.vocab_size * c.hidden + c.hidden
