"""Llama prefill/decode over the paged KV pool.

Same math as the dense paths in :mod:`langstream_tpu.models.llama`; only the
cache geometry changes: K/V rows live in pool blocks mapped by per-slot
block tables (:mod:`langstream_tpu.models.paged`). Decode attention runs in
two segments — the paged pool (Pallas kernel or XLA gather reference) and
the in-chunk KV buffer — merged with the associative online-softmax combine
(:func:`merge_partial_attention`).

Parity: the dense/paged pair mirrors the reference's single code path the
way vLLM relates to naive HF decoding — the capability (continuous batching
at fixed HBM) is SURVEY §7 build-order item 6.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp

from langstream_tpu.models.llama import (
    LlamaConfig,
    _apply_rope,
    _default_ffn,
    _rms_norm,
    _rope,
    lora_delta,
)
from langstream_tpu.models.paged import gather_kv, write_rows
from langstream_tpu.models.quant import as_weight as _w, embedding_take
from langstream_tpu.ops.paged_attention import (
    NEG_INF,
    merge_partial_attention,
    paged_attention_partial,
)


def llama_prefill_paged(
    config: LlamaConfig,
    params: dict,
    tokens: jax.Array,        # (B, P) int32, right-padded
    lengths: jax.Array,       # (B,) true lengths
    pool_k: jax.Array,        # (L, nb, bs, Kh*D)
    pool_v: jax.Array,
    block_tables: jax.Array,  # (B, max_blocks) int32 — rows for THIS batch
    use_flash: bool | None = None,
    mesh=None,
    ffn=None,                 # pluggable FFN sub-block (MoE family hook)
    adapters: dict | None = None,  # batched ragged LoRA (see lora_delta)
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Prompt forward + paged cache fill: the shared
    :func:`~langstream_tpu.models.llama.prefill_forward` layer math with the
    K/V landing in pool blocks — one scatter commit per K and V."""
    from langstream_tpu.models.llama import prefill_forward

    c = config
    B, Pn = tokens.shape
    logits, ks, vs = prefill_forward(
        c, params, tokens, lengths, use_flash, mesh=mesh, ffn=ffn,
        adapters=adapters,
    )
    KhD = c.kv_heads * c.head_dim
    L = ks.shape[0]
    valid = (jnp.arange(Pn)[None, :] < lengths[:, None])
    starts = jnp.zeros((B,), dtype=jnp.int32)
    pool_k = write_rows(pool_k, ks.reshape(L, B, Pn, KhD), block_tables, starts, valid)
    pool_v = write_rows(pool_v, vs.reshape(L, B, Pn, KhD), block_tables, starts, valid)
    return logits, pool_k, pool_v


def llama_prefill_continue_paged(
    config: LlamaConfig,
    params: dict,
    tokens: jax.Array,         # (B, P2) SUFFIX tokens, right-padded
    start_lengths: jax.Array,  # (B,) tokens already in the pool per slot
    suffix_lengths: jax.Array, # (B,) true suffix lengths
    pool_k: jax.Array,         # (L, nb, bs, KhD)
    pool_v: jax.Array,
    block_tables: jax.Array,   # (B, max_blocks)
    num_read_blocks: int,      # static: block columns covering max(start)
    ffn=None,
    return_all_logits: bool = False,  # (B, P2, V) instead of last-token —
                                      # the speculative verify step scores
                                      # every draft position
    kernel: str = "xla",  # history-segment read: "xla" (blocked gather,
                          # every backend/mesh) | "pallas" |
                          # "pallas-interpret" (multi-query scalar-prefetch
                          # kernel; under a mesh it runs per-shard via
                          # shard_map — slots on dp, heads on tp)
    mesh=None,
    adapters: dict | None = None,  # batched ragged LoRA (see lora_delta)
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Prefill CONTINUATION: process a prompt suffix whose prefix K/V is
    already in the paged pool (positions ``[0, start)`` per slot).

    Two uses: (a) **automatic prefix caching** — requests sharing a prompt
    prefix (system preambles, RAG templates, chat history) skip recomputing
    it, attending to the shared blocks instead; (b) **chunked prefill** —
    long prompts in bounded pieces. Attention per suffix query merges two
    segments with the online-softmax combine: the pool window masked to
    columns ``< start``, and causal self-attention among the suffix.
    Suffix K/V is committed at ``start`` offsets (the same
    :func:`write_rows` the decode chunk uses). Returns the last REAL suffix
    token's logits plus the updated pools.

    No reference analogue: the reference's completions are SaaS calls
    (``ChatCompletionsStep.java``), so prompt caching was the provider's
    problem; in-tree serving makes it ours.
    """
    from langstream_tpu.models.llama import _default_ffn

    c = config
    if ffn is None:
        ffn = _default_ffn
    B, P2 = tokens.shape
    quant = isinstance(pool_k, dict)
    bs = (pool_k["q"] if quant else pool_k).shape[2]
    if quant and kernel != "xla":
        # the multi-query history-read kernel has no int8 twin yet (the
        # decode chunk's single-query kernel does); prefill continuations
        # are a small share of traffic — degrade, don't crash
        kernel = "xla"
    KhD = c.kv_heads * c.head_dim
    G = c.heads // c.kv_heads
    x = embedding_take(params["embed"], tokens)  # (B, P2, H)
    positions = start_lengths[:, None] + jnp.arange(P2)[None, :]
    cos, sin = _rope(positions, c.head_dim, c.rope_theta)
    pos_valid = jnp.arange(P2)[None, :] < suffix_lengths[:, None]  # (B, P2)
    scale = 1.0 / math.sqrt(c.head_dim)
    # suffix key-block size: online-softmax over key blocks bounds score
    # memory at O(P2·sbs) per step instead of O(P2·(start+P2)) — this is
    # what keeps arbitrarily long suffixes (chunked prefill) HBM-safe. The
    # block must divide P2 exactly (dynamic_slice clamps at the edge and
    # would misalign the position mask), so take gcd(P2, 128): power-of-two
    # engine buckets get the full 128; awkward widths degrade the block
    # size, never the memory bound.
    sbs = math.gcd(P2, 128)
    n_suffix_blocks = P2 // sbs

    def layer(x, layer_in):
        if adapters is None:
            lp, ck_l, cv_l = layer_in
        else:
            lp, al, ck_l, cv_l = layer_in
        h = _rms_norm(x, lp["attn_norm"], c.norm_eps)
        q = jnp.einsum("bph,hd->bpd", h, _w(lp["wq"]))
        k = jnp.einsum("bph,hd->bpd", h, _w(lp["wk"]))
        v = jnp.einsum("bph,hd->bpd", h, _w(lp["wv"]))
        if adapters is not None:
            ids = adapters["ids"]
            q = q + lora_delta(h, ids, al["wq_a"], al["wq_b"])
            k = k + lora_delta(h, ids, al["wk_a"], al["wk_b"])
            v = v + lora_delta(h, ids, al["wv_a"], al["wv_b"])
        q = q.reshape(B, P2, c.heads, c.head_dim)
        k = k.reshape(B, P2, c.kv_heads, c.head_dim)
        v = v.reshape(B, P2, c.kv_heads, c.head_dim)
        q = _apply_rope(q, cos, sin)
        k = _apply_rope(k, cos, sin)
        qg = q.reshape(B, P2, c.kv_heads, G, c.head_dim)

        m0 = jnp.full((B, c.kv_heads, G, P2), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, c.kv_heads, G, P2), jnp.float32)
        o0 = jnp.zeros((B, c.kv_heads, G, P2, c.head_dim), jnp.float32)

        # the kvquant helpers work on (B, Kh, G', T/D) — fold the query
        # axis into G (one source of truth for the int8 scale-folding
        # identities; the reshapes touch only score-sized tensors)
        qg_flat = qg.transpose(0, 2, 3, 1, 4).reshape(
            B, c.kv_heads, G * P2, c.head_dim
        )

        def online_update(carry, k_blk, v_blk, mask_blk):
            # one flash-attention style block update: k/v (B, T, Kh, D) —
            # bf16 arrays, or int8 {"q","s"} pairs read through the fused
            # kvquant helpers — mask (B, 1, 1, P2?, T) broadcastable over
            # (B,Kh,G,P2,T)
            from langstream_tpu.models.kvquant import cache_scores, cache_values

            o, l, m = carry
            T = (k_blk["s"] if isinstance(k_blk, dict) else k_blk).shape[1]
            s = cache_scores(qg_flat, k_blk).reshape(
                B, c.kv_heads, G, P2, T
            ) * scale
            s = jnp.where(mask_blk, s, NEG_INF)
            m_new = jnp.maximum(m, s.max(axis=-1))
            shift = jnp.where(m_new <= NEG_INF, 0.0, m_new)
            p = jnp.where(mask_blk, jnp.exp(s - shift[..., None]), 0.0)
            alpha = jnp.exp(jnp.where(m <= NEG_INF, NEG_INF, m - shift))
            l = l * alpha + p.sum(axis=-1)
            update = cache_values(
                p.astype(qg.dtype).reshape(B, c.kv_heads, G * P2, T), v_blk
            ).reshape(B, c.kv_heads, G, P2, c.head_dim)
            o = o * alpha[..., None] + update.astype(jnp.float32)
            return o, l, m_new

        if kernel != "xla":
            # multi-query scalar-prefetch kernel: no densified gather, the
            # block table drives the DMA (ops/paged_attention.py)
            from langstream_tpu.ops.paged_attention import (
                paged_attention_multiquery_partial,
            )

            # keep (t_block·G)-row MXU tiles even for narrow suffixes
            # (speculative verify runs D1 = 1+drafts wide): history
            # attention is mask-uniform across queries, so padded rows
            # compute harmless extra attention that is sliced away
            tb = min(16, -(-P2 // 8) * 8)
            P2p = -(-P2 // tb) * tb
            qk = (
                jnp.pad(q, ((0, 0), (0, P2p - P2), (0, 0), (0, 0)))
                if P2p != P2
                else q
            )

            def mq_partial(q_, ck_, cv_, tables_, starts_, kv_heads):
                return paged_attention_multiquery_partial(
                    q_, ck_, cv_, tables_, starts_,
                    num_read_blocks=num_read_blocks,
                    kv_heads=kv_heads, head_dim=c.head_dim, t_block=tb,
                    scale=scale, interpret=(kernel == "pallas-interpret"),
                )

            if mesh is not None and len(mesh.devices.flatten()) > 1:
                # pallas_call has no SPMD rule: shared mesh wrapper — slots
                # on dp, heads on tp, per-axis degradation
                from langstream_tpu.ops.paged_attention import (
                    shard_mapped_paged_read,
                )

                acc_h, m_h, l_h = shard_mapped_paged_read(
                    mq_partial, mesh,
                    kv_heads=c.kv_heads, batch=B,
                    q_spec_tail=(None, "tp", None),       # (B, P2p, H, D)
                    out_spec_tails=(
                        (None, "tp", None),               # acc (B,T,H,D)
                        (None, "tp"),                     # m (B,T,H)
                        (None, "tp"),                     # l (B,T,H)
                    ),
                )(qk, ck_l, cv_l, block_tables, start_lengths)
            else:
                acc_h, m_h, l_h = mq_partial(
                    qk, ck_l, cv_l, block_tables, start_lengths,
                    kv_heads=c.kv_heads,
                )
            acc_h = acc_h[:, :P2]
            m_h, l_h = m_h[:, :P2], l_h[:, :P2]
            # (B, P2, H[, D]) → the (B, Kh, G, P2[, D]) carry layout
            carry = (
                acc_h.reshape(B, P2, c.kv_heads, G, c.head_dim).transpose(
                    0, 2, 3, 1, 4
                ),
                l_h.reshape(B, P2, c.kv_heads, G).transpose(0, 2, 3, 1),
                m_h.reshape(B, P2, c.kv_heads, G).transpose(0, 2, 3, 1),
            )
        else:
            # segment 1: pool history, ~128 rows of table columns per step
            # (one tiny per-pool-block step would serialize the sweep
            # ~128/bs-fold deeper for the same score memory)
            cps = max(1, 128 // bs)                         # columns/step
            n_hist_steps = -(-num_read_blocks // cps)

            def hist_step(carry, t):
                col_idx = t * cps + jnp.arange(cps)         # (cps,)
                safe = jnp.minimum(col_idx, num_read_blocks - 1)
                cols = jnp.take(block_tables, safe, axis=1)  # (B, cps)

                def take_blk(pool_l):
                    if isinstance(pool_l, dict):
                        return {
                            "q": jnp.take(pool_l["q"], cols, axis=0).reshape(
                                B, cps * bs, c.kv_heads, c.head_dim
                            ),
                            "s": jnp.take(pool_l["s"], cols, axis=0).reshape(
                                B, cps * bs, c.kv_heads
                            ),
                        }
                    return jnp.take(pool_l, cols, axis=0).reshape(
                        B, cps * bs, c.kv_heads, c.head_dim
                    )

                k_blk = take_blk(ck_l)
                v_blk = take_blk(cv_l)
                # positions from the UNclamped indices: a clamped
                # (duplicate) tail column computes positions ≥
                # num_read_blocks·bs, which the < start mask never admits
                w_pos = (
                    col_idx[:, None] * bs + jnp.arange(bs)[None, :]
                ).reshape(-1)
                mask = (w_pos[None, :] < start_lengths[:, None])[
                    :, None, None, None, :
                ]
                return online_update(carry, k_blk, v_blk, mask), None

            carry, _ = jax.lax.scan(
                hist_step, (o0, l0, m0), jnp.arange(n_hist_steps)
            )

        # segment 2: causal self-attention among the suffix, key-blocked
        def suf_step(carry, t):
            k_blk = jax.lax.dynamic_slice_in_dim(k, t * sbs, sbs, axis=1)
            v_blk = jax.lax.dynamic_slice_in_dim(v, t * sbs, sbs, axis=1)
            k_pos = t * sbs + jnp.arange(sbs)
            mask = (
                (jnp.arange(P2)[:, None] >= k_pos[None, :])[None]
                & (k_pos[None, None, :] < suffix_lengths[:, None, None])
            )[:, None, None, :, :]
            return online_update(carry, k_blk, v_blk, mask), None

        (o, l, m), _ = jax.lax.scan(
            suf_step, carry, jnp.arange(n_suffix_blocks)
        )
        inv = jnp.where(l > 0.0, 1.0 / jnp.maximum(l, 1e-30), 0.0)
        out = (o * inv[..., None]).astype(x.dtype)  # (B, Kh, G, P2, D)
        out = out.transpose(0, 3, 1, 2, 4).reshape(B, P2, c.heads * c.head_dim)
        attn = jnp.einsum("bpd,dh->bph", out, _w(lp["wo"]))
        if adapters is not None:
            attn = attn + lora_delta(out, adapters["ids"], al["wo_a"], al["wo_b"])
        x = x + attn
        h2 = _rms_norm(x, lp["mlp_norm"], c.norm_eps)
        x = x + ffn(h2, lp, pos_valid)
        return x, (k, v)

    layer_xs = (
        (params["layers"], pool_k, pool_v)
        if adapters is None
        else (params["layers"], adapters["layers"], pool_k, pool_v)
    )
    x, (ks, vs) = jax.lax.scan(layer, x, layer_xs)
    x = _rms_norm(x, params["final_norm"], c.norm_eps)
    if return_all_logits:
        logits = jnp.einsum("bph,hv->bpv", x, _w(params["lm_head"])).astype(
            jnp.float32
        )
    else:
        last = jnp.take_along_axis(
            x, (suffix_lengths - 1)[:, None, None].clip(0), axis=1
        ).squeeze(1)
        logits = jnp.einsum("bh,hv->bv", last, _w(params["lm_head"])).astype(
            jnp.float32
        )
    L = c.layers
    pool_k = write_rows(
        pool_k, ks.reshape(L, B, P2, KhD), block_tables, start_lengths, pos_valid
    )
    pool_v = write_rows(
        pool_v, vs.reshape(L, B, P2, KhD), block_tables, start_lengths, pos_valid
    )
    return logits, pool_k, pool_v


def pack_tokens_logprobs(tokens: jax.Array, logprobs: jax.Array) -> jax.Array:
    """Fold a chunk's host-bound outputs into ONE int32 buffer *inside*
    the decode program: tokens first, then the logprobs bit-cast to int32
    (lossless — the host views the tail back as float32). The engine's
    per-chunk host traffic is exactly this array's D2H copy; packing here
    rather than in a second jitted program removes the post-hoc pack
    dispatch from the decode tail."""
    return jnp.concatenate([
        tokens.astype(jnp.int32).reshape(-1),
        jax.lax.bitcast_convert_type(
            logprobs.astype(jnp.float32), jnp.int32
        ).reshape(-1),
    ])


def prompt_lookup_draft(
    ctx: jax.Array,         # (S,) int32 — [prompt | generated], zero-padded
    n: jax.Array,           # scalar int32 — valid tokens in ``ctx``
    num_drafts: int,
) -> tuple[jax.Array, jax.Array]:
    """Device twin of the engine's host bigram drafter: continue the
    context's most recent occurrence of its final bigram.

    Matches the host semantics exactly (the greedy speculative stream is
    byte-identity-pinned against plain decode, so the drafter must too):
    candidate positions are ``i in [1, n-2]`` with
    ``(ctx[i-1], ctx[i]) == (ctx[n-2], ctx[n-1])``, the LAST occurrence
    wins, and the draft is ``ctx[i+1 : i+1+num_drafts]`` clipped to the
    valid region and zero-padded. No match (or ``n < 3``) → all zeros
    with zero real drafts. Returns ``(drafts (num_drafts,), n_real)``.
    """
    S = ctx.shape[0]
    pos = jnp.arange(S, dtype=jnp.int32)
    last0 = ctx[jnp.maximum(n - 2, 0)]
    last1 = ctx[jnp.maximum(n - 1, 0)]
    prev = jnp.roll(ctx, 1)  # prev[i] = ctx[i-1]; prev[0] is masked out
    match = (prev == last0) & (ctx == last1) & (pos >= 1) & (pos <= n - 2)
    i = jnp.max(jnp.where(match, pos, -1))
    found = (i >= 0) & (n >= 3)
    start = i + 1
    offs = start + jnp.arange(num_drafts, dtype=jnp.int32)
    drafts = jnp.where(
        (offs < n) & found, ctx[jnp.clip(offs, 0, S - 1)], 0
    )
    n_real = jnp.where(found, jnp.clip(n - start, 0, num_drafts), 0)
    return drafts.astype(jnp.int32), n_real.astype(jnp.int32)


def llama_spec_step_paged(
    config: LlamaConfig,
    params: dict,
    ctx: jax.Array,            # (B, S) int32 device-resident context tokens
    current: jax.Array,        # (B,) last emitted token per slot
    base_lengths: jax.Array,   # (B,) tokens committed in the pool
    active: jax.Array,         # (B,) bool
    pool_k: jax.Array,
    pool_v: jax.Array,
    block_tables: jax.Array,
    num_drafts: int,
    num_read_blocks: int,
    ffn=None,
    kernel: str = "xla",
    mesh=None,
    key: jax.Array | None = None,
    temps: jax.Array | None = None,
    topks: jax.Array | None = None,
    topps: jax.Array | None = None,
    sampler_mode: tuple | None = None,
    adapters: dict | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fused device-resident speculative step: prompt-lookup draft over
    the resident context rows, the verify forward, and the in-program
    context update — ONE dispatch and ONE packed host fetch per step.

    The context rows hold ``[prompt | generated]`` so ``n = lengths + 1``
    (``current`` is ``ctx[n-1]``, not yet committed to the pool). Drafts
    are computed per-row by :func:`prompt_lookup_draft`, verified by
    :func:`llama_verify_chunk_paged`, and the emitted run is scattered
    back into ``ctx`` at ``n .. n+adv-1`` so the next step drafts from an
    already-current device context — the host never ships tokens back.

    Returns ``(packed, ctx, pool_k, pool_v)`` where ``packed`` is the
    int32 single-fetch layout
    ``[emitted (B*D1) | adv (B) | next (B) | new_lengths (B) |
    n_real (B) | bitcast logprobs (B*D1)]``.
    """
    c = config
    B, S = ctx.shape
    n = base_lengths.astype(jnp.int32) + 1
    drafts, n_real = jax.vmap(
        lambda row, ln: prompt_lookup_draft(row, ln, num_drafts)
    )(ctx, n)
    drafts = jnp.where(active[:, None], drafts, 0)
    n_real = jnp.where(active, n_real, 0)
    tokens = jnp.concatenate([current[:, None], drafts], axis=1)  # (B, D1)
    emitted, adv, next_tokens, new_lengths, pool_k, pool_v, logprobs = (
        llama_verify_chunk_paged(
            c, params, tokens, base_lengths, active, pool_k, pool_v,
            block_tables, num_read_blocks, ffn=ffn, kernel=kernel,
            mesh=mesh, key=key, temps=temps, topks=topks, topps=topps,
            sampler_mode=sampler_mode, adapters=adapters,
        )
    )
    D1 = num_drafts + 1
    js = jnp.arange(D1, dtype=jnp.int32)[None, :]
    write_pos = n[:, None] + js                    # emitted[:, j] → ctx[n+j]
    rows = jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32)[:, None], (B, D1))
    # unemitted columns (and context-cap overruns) redirect to an OOB
    # column and drop — inactive rows have adv 0, so they never write
    cols = jnp.where(js < adv[:, None], write_pos, S)
    ctx = ctx.at[rows, cols].set(emitted.astype(jnp.int32), mode="drop")
    packed = jnp.concatenate([
        emitted.astype(jnp.int32).reshape(-1),
        adv.astype(jnp.int32),
        next_tokens.astype(jnp.int32),
        new_lengths.astype(jnp.int32),
        n_real.astype(jnp.int32),
        jax.lax.bitcast_convert_type(
            logprobs.astype(jnp.float32), jnp.int32
        ).reshape(-1),
    ])
    return packed, ctx, pool_k, pool_v


def llama_verify_chunk_paged(
    config: LlamaConfig,
    params: dict,
    tokens: jax.Array,         # (B, D1): [current, draft_0 .. draft_{D1-2}]
    base_lengths: jax.Array,   # (B,) tokens in the pool per slot
    active: jax.Array,         # (B,) bool
    pool_k: jax.Array,
    pool_v: jax.Array,
    block_tables: jax.Array,
    num_read_blocks: int,
    ffn=None,
    kernel: str = "xla",  # history read (see llama_prefill_continue_paged)
    mesh=None,
    key: jax.Array | None = None,
    temps: jax.Array | None = None,
    topks: jax.Array | None = None,
    topps: jax.Array | None = None,
    sampler_mode: tuple | None = None,  # (use_top_p, use_top_k, all_greedy)
    adapters: dict | None = None,  # batched ragged LoRA (see lora_delta)
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Speculative VERIFY step (prompt-lookup decoding).

    One forward over ``D1 = 1 + drafts`` positions per slot scores every
    draft in parallel. Two acceptance modes, selected by the static
    ``sampler_mode`` (None or ``all_greedy`` → greedy):

    - **Greedy** (the default): in-jit greedy acceptance keeps the longest
      prefix of drafts the model itself would have produced, plus the
      model's one bonus token after it. Drafts cost nothing when wrong
      (acceptance only ever emits model-argmax tokens, so on a bf16 pool
      output streams are IDENTICAL to plain greedy decode — speculation
      changes latency, never content). On an int8 pool the guarantee is
      per-forward, not cross-engine: a position reads as fresh bf16 before
      commit and as quantised int8 after, and verify commits at different
      boundaries than the fixed decode chunk — near-tie argmaxes may
      differ (~1e-2 logit scale) from a non-speculative engine's stream.
    - **Sampled** (``sampler_mode`` set and not all-greedy): rejection
      sampling against the deterministic prompt-lookup drafter
      (``sampler.speculative_accept``) — draft ``d_j`` survives with the
      target's filtered probability ``p_j(d_j)``; the first rejection
      emits a residual sample; full acceptance earns a bonus sample. The
      emitted stream is distributed exactly as plain sampling. Greedy
      rows inside a mixed batch degenerate to the greedy rule.

    Returns (emitted (B, D1) — the token to emit at each position,
    emit_counts (B,) — how many leading emitted tokens are real (1..D1),
    next_tokens (B,), new_lengths (B,), pool_k, pool_v, logprobs (B, D1)).

    K/V for all D1 positions is committed; rows past ``new_lengths`` hold
    rejected drafts but every read masks to < length and the next step's
    writes land exactly at ``new_lengths`` — the standard stale-row
    argument of the prefill paths.
    """
    c = config
    B, D1 = tokens.shape
    # inactive rows get suffix length 0: their writes redirect to the
    # scratch block instead of committing garbage through their REAL block
    # tables (a mid-chunked-prefill slot, or shared prefix blocks, would
    # otherwise be silently corrupted — the decode chunk masks its commit
    # with `active` for exactly this reason). Rows are also capped at the
    # context limit: positions ≥ max_seq_len would clamp to the slot's
    # LAST table column in write_rows and overwrite committed K/V (the
    # engine's emit guard stops streams before any such position's token
    # is ever emitted, so capping the write loses nothing).
    room = jnp.maximum(c.max_seq_len - base_lengths, 0)
    suffix_lengths = jnp.where(
        active, jnp.minimum(D1, room), 0
    ).astype(jnp.int32)
    logits, pool_k, pool_v = llama_prefill_continue_paged(
        c, params, tokens, base_lengths,
        suffix_lengths, pool_k, pool_v, block_tables,
        num_read_blocks, ffn=ffn, return_all_logits=True, kernel=kernel,
        mesh=mesh, adapters=adapters,
    )  # logits (B, D1, V)
    drafts = tokens[:, 1:]                                   # (B, D1-1)
    logits_f32 = logits.astype(jnp.float32)
    if sampler_mode is None or sampler_mode[2]:  # all-greedy
        model_next = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, D1)
        # draft j (= input position j+1) is accepted iff every earlier
        # draft matched and the model's token at position j equals it
        match = model_next[:, :-1] == drafts                 # (B, D1-1)
        accepted = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
        emitted = model_next
    else:
        from langstream_tpu.serving.sampler import speculative_accept

        use_top_p, use_top_k, _ = sampler_mode
        accepted, fallback = speculative_accept(
            logits_f32, drafts, key, temps, topks, topps,
            use_top_p=use_top_p, use_top_k=use_top_k,
        )
        # emit accepted drafts verbatim, then the residual/bonus sample at
        # the stop position (the only fallback column the engine reads)
        pos = jnp.arange(D1)[None, :]
        drafts_pad = jnp.pad(drafts, ((0, 0), (0, 1)))
        emitted = jnp.where(pos < accepted[:, None], drafts_pad, fallback)
        emitted = emitted.astype(jnp.int32)
    logprobs = jnp.take_along_axis(
        jax.nn.log_softmax(logits_f32, axis=-1), emitted[..., None], axis=-1
    ).squeeze(-1)
    adv = jnp.where(active, accepted + 1, 0)                 # tokens emitted
    new_lengths = base_lengths + adv
    next_tokens = jnp.where(
        active,
        jnp.take_along_axis(
            emitted, jnp.maximum(adv - 1, 0)[:, None], axis=1
        ).squeeze(1),
        tokens[:, 0],
    )
    return emitted, adv, next_tokens, new_lengths, pool_k, pool_v, logprobs


def _gather_layer_window(c, pool_l, block_tables, num_read_blocks):
    """Densify one layer's window: (B, W, Kh, D) bf16, or the int8
    {"q": (B,W,Kh,D), "s": (B,W,Kh)} pair ready for the kvquant helpers."""
    add_l = lambda a: a[None]
    drop_l = lambda a: a[0]
    if isinstance(pool_l, dict):
        w = gather_kv(jax.tree.map(add_l, pool_l), block_tables, num_read_blocks)
        B, W = w["s"].shape[1:3]
        return {
            "q": w["q"][0].reshape(B, W, c.kv_heads, c.head_dim),
            "s": w["s"][0],
        }
    w = drop_l(gather_kv(add_l(pool_l), block_tables, num_read_blocks))
    B, W = w.shape[:2]
    return w.reshape(B, W, c.kv_heads, c.head_dim)


def _cache_partial_xla(
    c: LlamaConfig,
    q: jax.Array,             # (B, H, D)
    ck_l,                     # (nb, bs, KhD) array or int8 {"q","s"} pool
    cv_l,
    block_tables: jax.Array,  # (B, max_blocks)
    lengths: jax.Array,       # (B,)
    num_read_blocks: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Reference paged read: gather the window densely, compute partial
    softmax stats. Works on every backend and under pjit meshes (gathers
    shard like any XLA op); pays one densified copy. int8 pools read
    through the fused kvquant helpers (scales onto scores/probs)."""
    from langstream_tpu.models.kvquant import cache_scores, cache_values

    B, H, D = q.shape
    kw = _gather_layer_window(c, ck_l, block_tables, num_read_blocks)
    vw = _gather_layer_window(c, cv_l, block_tables, num_read_blocks)
    W = (kw["s"] if isinstance(kw, dict) else kw).shape[1]
    G = c.heads // c.kv_heads
    qg = q.reshape(B, c.kv_heads, G, c.head_dim)
    s = cache_scores(qg, kw) / math.sqrt(c.head_dim)
    mask = (jnp.arange(W)[None, :] < lengths[:, None])[:, None, None, :]
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # (B, Kh, G)
    shift = jnp.where(m <= NEG_INF, 0.0, m)
    p = jnp.exp(s - shift[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = cache_values(p.astype(q.dtype), vw).astype(jnp.float32)
    return (
        acc.reshape(B, H, D),
        m.reshape(B, H),
        l.reshape(B, H),
    )


def llama_decode_chunk_paged(
    config: LlamaConfig,
    params: dict,
    tokens0: jax.Array,       # (B,)
    base_lengths: jax.Array,  # (B,)
    active: jax.Array,        # (B,) bool
    pool_k: jax.Array,        # (L, nb, bs, KhD) — read-only during the chunk
    pool_v: jax.Array,
    block_tables: jax.Array,  # (B, max_blocks)
    sample_fn: Callable,
    key: jax.Array,
    num_steps: int,
    num_read_blocks: int,     # static block-sweep bucket (covers max length)
    kernel: str = "xla",      # "xla" | "pallas" | "pallas-interpret"
    mesh=None,                # Pallas kernel runs per-shard via shard_map
    ffn=None,                 # (h (B,H), lp, valid=None) -> (B,H);
                              # default dense SwiGLU
    sample_extras=None,       # (presences, frequencies, counts0) — see
                              # llama_decode_chunk
    adapters: dict | None = None,  # batched ragged LoRA (see lora_delta)
    return_packed: bool = False,
) -> tuple[jax.Array, ...]:
    """K fused decode steps against the paged pool; same two-segment
    discipline as the dense ``llama_decode_chunk`` (pool read-only, new K/V
    in a chunk buffer, one scatter commit at the end).

    ``return_packed=True`` folds the chunk's host-bound outputs into the
    program itself (:func:`pack_tokens_logprobs`) and returns
    ``(packed, final_tokens, final_lengths, pool_k, pool_v)`` — the
    engine's whole per-chunk host traffic becomes that one array's D2H
    copy, with no post-hoc pack dispatch."""
    c = config
    if ffn is None:
        ffn = _default_ffn
    if (
        isinstance(pool_k, dict)
        and kernel != "xla"
        and mesh is not None
        and len(mesh.devices.flatten()) > 1
    ):
        # the shard_map Pallas wrapper doesn't carry the int8 scale specs
        # yet; multi-device int8 pools stay on the (sharding-aware) XLA
        # gather. Single device reads through the in-kernel dequant twin.
        kernel = "xla"
    B = tokens0.shape[0]
    KhD = c.kv_heads * c.head_dim
    adv = active.astype(jnp.int32)
    kbuf0 = jnp.zeros((c.layers, B, num_steps, c.kv_heads, c.head_dim), c.dtype)
    vbuf0 = jnp.zeros_like(kbuf0)
    pen = sample_extras is not None
    counts0 = sample_extras[2] if pen else None

    def _kernel_partial(q, ck_l, cv_l, tables, lengths, kv_heads):
        return paged_attention_partial(
            q, ck_l, cv_l, tables, lengths,
            num_read_blocks=num_read_blocks,
            kv_heads=kv_heads, head_dim=c.head_dim,
            scale=1.0 / math.sqrt(c.head_dim),
            interpret=(kernel == "pallas-interpret"),
        )

    def cache_partial(q, ck_l, cv_l):
        if kernel == "xla":
            return _cache_partial_xla(
                c, q, ck_l, cv_l, block_tables, base_lengths, num_read_blocks
            )
        if mesh is not None and len(mesh.devices.flatten()) > 1:
            # pallas_call has no SPMD rule: shared mesh wrapper — slots on
            # dp, heads on tp, per-axis degradation
            from langstream_tpu.ops.paged_attention import (
                shard_mapped_paged_read,
            )

            return shard_mapped_paged_read(
                _kernel_partial, mesh,
                kv_heads=c.kv_heads, batch=B,
                q_spec_tail=("tp", None),                  # (B, H, D)
                out_spec_tails=(("tp", None), ("tp",), ("tp",)),
            )(q, ck_l, cv_l, block_tables, base_lengths)
        return _kernel_partial(
            q, ck_l, cv_l, block_tables, base_lengths, c.kv_heads
        )

    def step(carry, step_idx):
        if pen:
            tokens, kbuf, vbuf, key, counts = carry
        else:
            tokens, kbuf, vbuf, key = carry
            counts = None
        key, sub = jax.random.split(key)
        x = embedding_take(params["embed"], tokens)
        positions = base_lengths + step_idx * adv
        cos, sin = _rope(positions, c.head_dim, c.rope_theta)
        buf_mask = jnp.arange(num_steps)[None, :] <= step_idx  # (1, K)
        G = c.heads // c.kv_heads

        def layer(x, layer_in):
            if adapters is None:
                lp, ck_l, cv_l, kbuf_l, vbuf_l = layer_in
            else:
                lp, al, ck_l, cv_l, kbuf_l, vbuf_l = layer_in
            h = _rms_norm(x, lp["attn_norm"], c.norm_eps)
            q = h @ _w(lp["wq"])
            k = h @ _w(lp["wk"])
            v = h @ _w(lp["wv"])
            if adapters is not None:
                ids = adapters["ids"]
                q = q + lora_delta(h, ids, al["wq_a"], al["wq_b"])
                k = k + lora_delta(h, ids, al["wk_a"], al["wk_b"])
                v = v + lora_delta(h, ids, al["wv_a"], al["wv_b"])
            q = q.reshape(B, c.heads, c.head_dim)
            k = k.reshape(B, c.kv_heads, c.head_dim)
            v = v.reshape(B, c.kv_heads, c.head_dim)
            q = _apply_rope(q, cos, sin)
            k = _apply_rope(k, cos, sin)
            kbuf_l = jax.lax.dynamic_update_slice_in_dim(
                kbuf_l, k[:, None], step_idx, axis=1
            )
            vbuf_l = jax.lax.dynamic_update_slice_in_dim(
                vbuf_l, v[:, None], step_idx, axis=1
            )
            # segment 1: paged pool (partial stats)
            acc_c, m_c, l_c = cache_partial(q, ck_l, cv_l)
            # segment 2: in-chunk buffer (partial stats, tiny)
            qg = q.reshape(B, c.kv_heads, G, c.head_dim)
            s_buf = jnp.einsum("bkgd,btkd->bkgt", qg, kbuf_l).astype(jnp.float32)
            s_buf = s_buf / math.sqrt(c.head_dim)
            s_buf = jnp.where(buf_mask[:, None, None, :], s_buf, NEG_INF)
            m_b = jnp.max(s_buf, axis=-1)
            shift = jnp.where(m_b <= NEG_INF, 0.0, m_b)
            p_b = jnp.exp(s_buf - shift[..., None])
            p_b = jnp.where(buf_mask[:, None, None, :], p_b, 0.0)
            l_b = jnp.sum(p_b, axis=-1)
            acc_b = jnp.einsum(
                "bkgt,btkd->bkgd", p_b.astype(vbuf_l.dtype), vbuf_l
            ).astype(jnp.float32)
            out = merge_partial_attention([
                (acc_c, m_c, l_c),
                (
                    acc_b.reshape(B, c.heads, c.head_dim),
                    m_b.reshape(B, c.heads),
                    l_b.reshape(B, c.heads),
                ),
            ]).astype(x.dtype)
            out = out.reshape(B, c.heads * c.head_dim)
            attn = out @ _w(lp["wo"])
            if adapters is not None:
                attn = attn + lora_delta(
                    out, adapters["ids"], al["wo_a"], al["wo_b"]
                )
            x = x + attn
            h2 = _rms_norm(x, lp["mlp_norm"], c.norm_eps)
            x = x + ffn(h2, lp, active)
            return x, (kbuf_l, vbuf_l)

        layer_xs = (
            (params["layers"], pool_k, pool_v, kbuf, vbuf)
            if adapters is None
            else (params["layers"], adapters["layers"], pool_k, pool_v,
                  kbuf, vbuf)
        )
        x, (kbuf, vbuf) = jax.lax.scan(layer, x, layer_xs)
        x = _rms_norm(x, params["final_norm"], c.norm_eps)
        logits = (x @ _w(params["lm_head"])).astype(jnp.float32)
        if pen:
            nxt, lp_ = sample_fn(logits, sub, counts)
        else:
            nxt, lp_ = sample_fn(logits, sub)
        nxt = jnp.where(active, nxt, tokens)
        if pen:
            counts = counts.at[jnp.arange(B), nxt].add(adv)
            return (nxt, kbuf, vbuf, key, counts), (nxt, lp_)
        return (nxt, kbuf, vbuf, key), (nxt, lp_)

    carry0 = (
        (tokens0, kbuf0, vbuf0, key, counts0)
        if pen
        else (tokens0, kbuf0, vbuf0, key)
    )
    out_carry, (chunk_tokens, chunk_lps) = jax.lax.scan(
        step, carry0, jnp.arange(num_steps)
    )
    final_tokens, kbuf, vbuf = out_carry[0], out_carry[1], out_carry[2]

    L = c.layers
    valid = jnp.broadcast_to(active[:, None], (B, num_steps))
    pool_k = write_rows(
        pool_k, kbuf.reshape(L, B, num_steps, KhD), block_tables,
        base_lengths, valid,
    )
    pool_v = write_rows(
        pool_v, vbuf.reshape(L, B, num_steps, KhD), block_tables,
        base_lengths, valid,
    )
    final_lengths = base_lengths + num_steps * adv
    if return_packed:
        packed = pack_tokens_logprobs(chunk_tokens, chunk_lps)
        return packed, final_tokens, final_lengths, pool_k, pool_v
    return chunk_tokens, chunk_lps, final_tokens, final_lengths, pool_k, pool_v


def llama_decode_chunk_dense_pallas(
    config: LlamaConfig,
    params: dict,
    tokens0: jax.Array,
    base_lengths: jax.Array,
    active: jax.Array,
    cache_k: jax.Array,       # (L, B, S, Kh, D) — the DENSE layout
    cache_v: jax.Array,
    sample_fn: Callable,
    key: jax.Array,
    num_steps: int,
    window: int | None,
    kernel: str = "pallas",
    block_size: int = 128,
    ffn=None,                 # pluggable FFN sub-block (MoE family hook)
    sample_extras=None,       # (presences, frequencies, counts0)
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Dense-cache decode through the PAGED Pallas read kernel.

    A dense cache is a degenerate block pool: slot ``b``'s rows are the
    contiguous blocks ``[b*S/bs, (b+1)*S/bs)``, so reshaping the cache to
    ``(L, B·S/bs, bs, Kh·D)`` and handing the kernel identity block tables
    reuses the tested scalar-prefetch kernel verbatim — no densified gather,
    no second kernel to maintain. The XLA einsum path stays the reference
    (and the mesh path); this is the single-chip TPU fast path where the
    GQA einsum's 2-row MXU tiles leave throughput on the table.
    """
    c = config
    L, B, S, Kh, D = cache_k.shape
    if S % block_size:
        raise ValueError(f"max_seq_len {S} not divisible by {block_size}")
    nb = S // block_size
    pool_k = cache_k.reshape(L, B * nb, block_size, Kh * D)
    pool_v = cache_v.reshape(L, B * nb, block_size, Kh * D)
    tables = (
        jnp.arange(B, dtype=jnp.int32)[:, None] * nb
        + jnp.arange(nb, dtype=jnp.int32)[None, :]
    )
    rows = window if window is not None else S
    num_read_blocks = max(1, min(-(-rows // block_size), nb))
    out = llama_decode_chunk_paged(
        c, params, tokens0, base_lengths, active, pool_k, pool_v, tables,
        sample_fn, key, num_steps, num_read_blocks=num_read_blocks,
        kernel=kernel, ffn=ffn, sample_extras=sample_extras,
    )
    chunk_t, chunk_lp, final_t, final_l, pk, pv = out
    return (
        chunk_t, chunk_lp, final_t, final_l,
        pk.reshape(L, B, S, Kh, D), pv.reshape(L, B, S, Kh, D),
    )
