"""Mixture-of-Experts decoder (Mixtral-family), pure JAX, TPU-first.

Design (vs. a torch port of Mixtral):

- **Capacity-based top-2 dispatch as one-hot matmuls** (GShard style): the
  dispatch/combine tensors are einsummed on the MXU — no scatter/gather, no
  dynamic shapes, so XLA tiles everything. Tokens overflowing an expert's
  capacity fall through the residual (standard GShard semantics).
- **Expert parallelism over the ``ep`` mesh axis**: expert weights are
  sharded ``P("ep", ...)``; the dispatch einsum contracts a ``dp``-sharded
  token axis against an ``ep``-sharded expert axis, so XLA inserts the
  all-to-all over ICI — no hand-written collectives.
- **TP composes inside each expert**: expert up/gate column-sharded on
  ``tp``, down row-sharded, same Megatron rule as the dense model.
- Attention blocks are exactly the Llama ones (imported), so every
  parallelism mode of the dense path (ring/Ulysses sp, flash prefill)
  composes with MoE FFNs.

Capability parity: the reference serves MoE SaaS models (e.g. Mixtral via
Ollama/HF providers, ``HuggingFaceProvider.java:47``); here the MoE family
is in-tree and TPU-resident.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from langstream_tpu.models.llama import (
    _rms_norm,
    _rope,
    attention_block,
)


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    hidden: int = 4096
    layers: int = 32
    heads: int = 32
    kv_heads: int = 8
    head_dim: int = 128
    moe_intermediate: int = 14336
    experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    rope_theta: float = 1000000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 4096
    dtype: Any = jnp.bfloat16

    @classmethod
    def mixtral_8x7b(cls, max_seq_len: int = 4096) -> "MoEConfig":
        return cls(max_seq_len=max_seq_len)

    @classmethod
    def tiny(cls, max_seq_len: int = 128) -> "MoEConfig":
        return cls(
            vocab_size=384, hidden=64, layers=2, heads=4, kv_heads=2,
            head_dim=16, moe_intermediate=128, experts=4,
            experts_per_token=2, max_seq_len=max_seq_len,
        )

    def capacity(self, tokens: int) -> int:
        """Static per-expert capacity for a batch of ``tokens``."""
        return max(
            1,
            int(
                math.ceil(
                    self.experts_per_token * tokens * self.capacity_factor
                    / self.experts
                )
            ),
        )


def init_moe_params(config: MoEConfig, key: jax.Array | None = None) -> dict:
    key = key if key is not None else jax.random.PRNGKey(0)
    c = config
    keys = jax.random.split(key, 12)
    qkv_dim = c.heads * c.head_dim
    kv_dim = c.kv_heads * c.head_dim
    L, E, I = c.layers, c.experts, c.moe_intermediate

    def w_init(k, *shape, fan_in):
        scale = 1.0 / math.sqrt(fan_in)
        return (jax.random.normal(k, shape, dtype=jnp.float32) * scale).astype(
            c.dtype
        )

    return {
        "embed": w_init(keys[0], c.vocab_size, c.hidden, fan_in=c.hidden),
        "layers": {
            "attn_norm": jnp.ones((L, c.hidden), dtype=c.dtype),
            "wq": w_init(keys[1], L, c.hidden, qkv_dim, fan_in=c.hidden),
            "wk": w_init(keys[2], L, c.hidden, kv_dim, fan_in=c.hidden),
            "wv": w_init(keys[3], L, c.hidden, kv_dim, fan_in=c.hidden),
            "wo": w_init(keys[4], L, qkv_dim, c.hidden, fan_in=qkv_dim),
            "mlp_norm": jnp.ones((L, c.hidden), dtype=c.dtype),
            # router stays float32: tiny, and routing decisions are
            # numerically delicate
            "router": jax.random.normal(
                keys[5], (L, c.hidden, E), dtype=jnp.float32
            ) * (1.0 / math.sqrt(c.hidden)),
            "w_gate": w_init(keys[6], L, E, c.hidden, I, fan_in=c.hidden),
            "w_up": w_init(keys[7], L, E, c.hidden, I, fan_in=c.hidden),
            "w_down": w_init(keys[8], L, E, I, c.hidden, fan_in=I),
        },
        "final_norm": jnp.ones((c.hidden,), dtype=c.dtype),
        "lm_head": w_init(keys[9], c.hidden, c.vocab_size, fan_in=c.hidden),
    }


def moe_param_specs(config: MoEConfig) -> dict:
    """Expert axis on ``ep``, Megatron TP inside each expert."""
    return {
        "embed": P(None, None),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "mlp_norm": P(None, None),
            "router": P(None, None, None),
            "w_gate": P(None, "ep", None, "tp"),
            "w_up": P(None, "ep", None, "tp"),
            "w_down": P(None, "ep", "tp", None),
        },
        "final_norm": P(None),
        "lm_head": P(None, "tp"),
    }


def shard_moe_params(params: dict, config: MoEConfig, mesh: Mesh) -> dict:
    specs = moe_param_specs(config)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: isinstance(x, P),
    )


# ---------------------------------------------------------------------------
# top-2 gating + dispatch
# ---------------------------------------------------------------------------


def top2_gating(
    router_logits: jax.Array,  # (B, S, E) float32
    capacity: int,
    valid: jax.Array | None = None,  # (B, S) bool; invalid positions take no
                                     # capacity and get zero combine weight
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """GShard top-2 gating with static capacity.

    Returns (dispatch (B,S,E,C) bool, combine (B,S,E,C) float32,
    aux_loss scalar — the load-balancing loss from the GShard/Switch papers).

    ``valid`` matters under serving: right-padded prefill positions and
    inactive decode slots would otherwise queue for (and evict real tokens
    from) expert capacity, making a prompt's logits depend on its batch
    neighbours' padding.
    """
    B, S, E = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)  # (B, S, E)

    idx1 = jnp.argmax(probs, axis=-1)                       # (B, S)
    mask1 = jax.nn.one_hot(idx1, E, dtype=probs.dtype)      # (B, S, E)
    if valid is not None:
        mask1 = mask1 * valid[..., None].astype(probs.dtype)
    p1 = jnp.sum(probs * mask1, axis=-1)                    # (B, S)

    probs2 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, E, dtype=probs.dtype)
    if valid is not None:
        mask2 = mask2 * valid[..., None].astype(probs.dtype)
    p2 = jnp.sum(probs * mask2, axis=-1)

    # renormalise the two winners (Mixtral semantics)
    denom = p1 + p2 + 1e-9
    w1, w2 = p1 / denom, p2 / denom

    # position of each token within its expert's queue, flattened over (B,S)
    flat1 = mask1.reshape(B * S, E)
    flat2 = mask2.reshape(B * S, E)
    pos1 = jnp.cumsum(flat1, axis=0) * flat1 - flat1        # 0-based
    pos2 = (jnp.cumsum(flat2, axis=0) + flat1.sum(0, keepdims=True)) * flat2 - flat2
    keep1 = (pos1 < capacity) & (flat1 > 0)
    keep2 = (pos2 < capacity) & (flat2 > 0)

    oh1 = jax.nn.one_hot(pos1.astype(jnp.int32), capacity, dtype=probs.dtype)
    oh2 = jax.nn.one_hot(pos2.astype(jnp.int32), capacity, dtype=probs.dtype)
    combine_flat = (
        w1.reshape(-1, 1, 1) * keep1[..., None] * oh1
        + w2.reshape(-1, 1, 1) * keep2[..., None] * oh2
    )  # (B*S, E, C)
    combine = combine_flat.reshape(B, S, E, capacity)
    dispatch = combine > 0.0

    # load-balancing auxiliary loss: E * Σ_e fraction_tokens_e · mean_prob_e
    density = mask1.reshape(B * S, E).mean(axis=0)
    density_proxy = probs.reshape(B * S, E).mean(axis=0)
    aux_loss = jnp.sum(density * density_proxy) * (E * E) / 2.0
    return dispatch, combine, aux_loss


def moe_ffn(
    x: jax.Array,            # (B, S, H)
    router_w: jax.Array,     # (H, E) float32
    w_gate: jax.Array,       # (E, H, I)
    w_up: jax.Array,         # (E, H, I)
    w_down: jax.Array,       # (E, I, H)
    capacity: int,
    ep_constrain=None,       # applied to (E, C', H) expert-major tensors
    valid: jax.Array | None = None,  # (B, S) bool — see top2_gating
) -> tuple[jax.Array, jax.Array]:
    """Top-2 MoE feed-forward; returns (output (B,S,H), aux_loss).

    The two einsums flanking the expert computation are the all-to-alls:
    tokens (sharded ``dp``/``sp``) → expert-major (sharded ``ep``) and back.
    """
    B, S, H = x.shape
    router_logits = jnp.einsum(
        "bsh,he->bse", x.astype(jnp.float32), router_w
    )
    dispatch, combine, aux = top2_gating(router_logits, capacity, valid=valid)
    dispatch = dispatch.astype(x.dtype)
    if ep_constrain is None:
        ep_constrain = lambda t: t  # noqa: E731
    # dispatch all-to-all: tokens → (E, C, H) expert-major
    xe = ep_constrain(jnp.einsum("bsec,bsh->ech", dispatch, x))
    gate = jax.nn.silu(jnp.einsum("ech,ehi->eci", xe, w_gate))
    up = jnp.einsum("ech,ehi->eci", xe, w_up)
    ye = ep_constrain(jnp.einsum("eci,eih->ech", gate * up, w_down))
    # combine all-to-all: expert-major → tokens
    out = jnp.einsum("bsec,ech->bsh", combine.astype(x.dtype), ye)
    return out, aux


# ---------------------------------------------------------------------------
# forward (training / prefill building block)
# ---------------------------------------------------------------------------


def moe_forward(
    config: MoEConfig,
    params: dict,
    tokens: jax.Array,  # (B, S)
    *,
    attention=None,
    constrain=None,     # activations (B,S,H)
    ep_constrain=None,  # expert-major intermediates (E,C,H)
) -> tuple[jax.Array, jax.Array]:
    """All-position logits (B, S, V) + summed aux loss. Same shape contract
    as :func:`llama_forward`, plus the MoE auxiliary load-balancing loss the
    training step adds to the CE loss."""
    c = config
    B, S = tokens.shape
    if attention is None:
        from langstream_tpu.parallel.ring import dense_attention
        from functools import partial

        attention = partial(
            dense_attention, causal=True, scale=1.0 / math.sqrt(c.head_dim)
        )
    if constrain is None:
        constrain = lambda x: x  # noqa: E731
    capacity = c.capacity(B * S)

    x = constrain(jnp.take(params["embed"], tokens, axis=0))
    positions = jnp.arange(S)[None, :].repeat(B, axis=0)
    cos, sin = _rope(positions, c.head_dim, c.rope_theta)

    def layer(carry, lp):
        x, aux_total = carry
        x = attention_block(c, x, lp, cos, sin, attention)
        h2 = _rms_norm(x, lp["mlp_norm"], c.norm_eps)
        ffn, aux = moe_ffn(
            h2, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
            capacity, ep_constrain=ep_constrain,
        )
        x = x + ffn
        return (constrain(x), aux_total + aux), None

    (x, aux_total), _ = jax.lax.scan(layer, (x, jnp.float32(0.0)), params["layers"])
    x = _rms_norm(x, params["final_norm"], c.norm_eps)
    logits = jnp.einsum("bsh,hv->bsv", x, params["lm_head"]).astype(jnp.float32)
    return logits, aux_total


def moe_forward_sharded(
    config: MoEConfig,
    params: dict,
    tokens: jax.Array,
    mesh: Mesh,
) -> tuple[jax.Array, jax.Array]:
    """Mesh-annotated MoE forward: activations on dp/sp, expert-major
    intermediates on ep (XLA materialises the dispatch/combine all-to-alls
    over ICI at those constraints)."""
    axes = mesh.axis_names
    dp = "dp" if "dp" in axes else None
    sp = "sp" if "sp" in axes else None
    ep = "ep" if "ep" in axes else None
    x_spec = NamedSharding(mesh, P(dp, sp, None))
    e_spec = NamedSharding(mesh, P(ep, None, None))
    return moe_forward(
        config, params, tokens,
        constrain=lambda x: jax.lax.with_sharding_constraint(x, x_spec),
        ep_constrain=lambda t: jax.lax.with_sharding_constraint(t, e_spec),
    )


def moe_serving_ffn(config: MoEConfig, ep_constrain=None):
    """FFN callback for the shared llama serving paths (prefill_forward /
    llama_decode_chunk / the paged twins): routes each position through the
    top-2 expert mix. Accepts ``(B, H)`` decode activations or ``(B, S, H)``
    prefill activations; understands int8-quantized expert weights.

    This is what makes MoE a *served* family, not just a trainable one —
    the reference can only reach MoE models through SaaS providers
    (``HuggingFaceProvider.java:47``); here Mixtral-class models run on the
    same continuous-batching engine as the dense Llamas.
    """
    from langstream_tpu.models.quant import as_weight

    def ffn(h: jax.Array, lp: dict, valid: jax.Array | None = None) -> jax.Array:
        squeeze = h.ndim == 2
        x = h[:, None, :] if squeeze else h
        if valid is not None and valid.ndim == 1:
            valid = valid[:, None]  # decode: (B,) active → (B, 1)
        B, S, _H = x.shape
        capacity = config.capacity(B * S)
        out, _aux = moe_ffn(
            x,
            lp["router"],
            as_weight(lp["w_gate"]),
            as_weight(lp["w_up"]),
            as_weight(lp["w_down"]),
            capacity,
            ep_constrain=ep_constrain,
            valid=valid,
        )
        return out[:, 0, :] if squeeze else out

    return ffn


def moe_param_count(config: MoEConfig) -> int:
    c = config
    attn = (
        c.hidden * c.heads * c.head_dim
        + 2 * c.hidden * c.kv_heads * c.head_dim
        + c.heads * c.head_dim * c.hidden
    )
    experts = c.experts * 3 * c.hidden * c.moe_intermediate
    per_layer = attn + experts + c.hidden * c.experts + 2 * c.hidden
    return c.layers * per_layer + 2 * c.vocab_size * c.hidden + c.hidden
