"""Paged KV cache: block-table layout + pure read/write/attention helpers.

Why paged: the dense cache ``(L, slots, S, Kh, D)`` reserves
``slots × max_seq_len`` rows of HBM up front, so slot count is capped by the
*worst-case* sequence length even when every live request is short. Paging
(vLLM-style) slices the cache into fixed ``block_size``-row blocks shared
from one pool; a slot holds ``ceil(len/bs)`` blocks, mapped by a small
host-managed block table. Capacity then scales with *actual* tokens
resident, not slots × S (reference parity: SURVEY §7 build-order item 6).

TPU-first layout: the pool is ``(L, num_blocks, block_size, Kh*D)`` — the
trailing two dims ``(block_size, Kh*D)`` are clean (8,128)-multiples, so
both XLA scatters/gathers and the Pallas kernel DMA whole tiles. All
functions here are jit-pure; the host side (free lists, reservations) lives
in :class:`BlockManager`.

Read paths:
- :func:`gather_kv` — XLA reference: gathers a slot's blocks into a dense
  window. Correct everywhere (CPU tests, sharded meshes); costs an extra
  HBM round-trip for the gathered copy.
- :mod:`langstream_tpu.ops.paged_attention` — Pallas kernel that walks the
  block table directly via scalar prefetch; no gathered copy. Single-chip
  TPU fast path.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class PagedLayout:
    """Static geometry of the paged pool."""

    block_size: int
    num_blocks: int
    max_blocks_per_slot: int

    @classmethod
    def for_model(
        cls,
        max_seq_len: int,
        slots: int,
        block_size: int = 64,
        hbm_fraction_of_dense: float = 0.5,
        num_blocks: int | None = None,
    ) -> "PagedLayout":
        """Size the pool to ``hbm_fraction_of_dense`` of what the dense
        cache would reserve (the whole point: same slot count, less HBM —
        or more slots at the same HBM)."""
        max_blocks_per_slot = -(-max_seq_len // block_size)
        if num_blocks is None:
            dense_rows = slots * max_seq_len
            num_blocks = max(
                slots + 1, int(dense_rows * hbm_fraction_of_dense) // block_size
            )
        return cls(
            block_size=block_size,
            num_blocks=num_blocks,
            max_blocks_per_slot=max_blocks_per_slot,
        )


def init_paged_kv_cache(
    config, layout: PagedLayout
) -> tuple[jax.Array, jax.Array]:
    """Pool arrays ``(L, num_blocks, block_size, Kh*D)`` for K and V."""
    c = config
    shape = (
        c.layers,
        layout.num_blocks,
        layout.block_size,
        c.kv_heads * c.head_dim,
    )
    return jnp.zeros(shape, dtype=c.dtype), jnp.zeros(shape, dtype=c.dtype)


def init_paged_kv_cache_int8(
    config, layout: PagedLayout
) -> tuple[dict, dict]:
    """int8 pools: data as :func:`init_paged_kv_cache` plus one f32 scale
    per (block row, kv-head) — the paged twin of
    :func:`langstream_tpu.models.kvquant.init_kv_cache_int8`."""
    c = config
    base = (c.layers, layout.num_blocks, layout.block_size)
    make = lambda: {
        "q": jnp.zeros(base + (c.kv_heads * c.head_dim,), dtype=jnp.int8),
        "s": jnp.zeros(base + (c.kv_heads,), dtype=jnp.float32),
    }
    return make(), make()


def paged_cache_spec(mesh_axes: tuple[str, ...]):
    """Pool (L, nb, bs, Kh*D): the trailing fused head axis shards on tp.
    Blocks are NOT sharded on dp (any slot may use any block), so paged
    serving shards the model, not the pool rows."""
    from jax.sharding import PartitionSpec as P

    tp = "tp" if "tp" in mesh_axes else None
    return P(None, None, None, tp)


# ---------------------------------------------------------------------------
# jit-pure read/write
# ---------------------------------------------------------------------------


def write_rows(
    cache,                  # (L, nb, bs, KhD) array, or int8 {"q","s"} pools
    rows: jax.Array,        # (L, B, T, KhD) — new bf16 K or V rows per slot
    block_tables: jax.Array,  # (B, max_blocks) int32
    starts: jax.Array,      # (B,) first sequence position of rows[;, b]
    valid: jax.Array,       # (B, T) bool — rows beyond a slot's true count
):
    """Scatter ``rows`` into the pool at each slot's block-mapped positions.

    Invalid rows are redirected to a scratch row (block 0 never backs live
    data; see BlockManager) so the scatter stays shape-static. An int8 pool
    quantises the rows here — write sites stay layout-agnostic. Rows that
    are ALREADY quantized (an int8 ``{"q","s"}`` pair, e.g. a KV handoff
    payload from another replica's identical pool) scatter verbatim, so a
    transfer never pays a dequant/requant round trip.
    """
    quant = isinstance(cache, dict)
    nb, bs, KhD = (cache["q"] if quant else cache).shape[1:]
    rows_data = rows["q"] if isinstance(rows, dict) else rows
    B, T = rows_data.shape[1], rows_data.shape[2]
    pos = starts[:, None] + jnp.arange(T)[None, :]          # (B, T)
    # clamp: invalid rows may compute positions past the table; they're
    # redirected to scratch below, the clamp just keeps indexing in-bounds
    block_idx = jnp.clip(pos // bs, 0, block_tables.shape[1] - 1)
    offset = pos % bs
    blocks = jnp.take_along_axis(block_tables, block_idx, axis=1)  # (B, T)
    flat = blocks * bs + offset                              # row in (nb*bs)
    # invalid rows land in block 0 (reserved scratch, never allocated), so
    # the scatter stays shape-static and garbage never touches live data
    flat = jnp.where(valid, flat, 0).reshape(-1)             # (B*T,)

    def scatter(pool, new_rows):  # trailing dims: KhD / Kh
        L = new_rows.shape[0]
        tail = pool.shape[3:]
        flat_cache = pool.reshape((L, nb * bs) + tail)
        flat_rows = new_rows.reshape((L, B * T) + tail)
        return flat_cache.at[:, flat].set(flat_rows).reshape(pool.shape)

    if not quant:
        return scatter(cache, rows)
    if isinstance(rows, dict):
        # pre-quantized rows (KV handoff): bit-exact pass-through
        return {
            "q": scatter(cache["q"], rows["q"]),
            "s": scatter(cache["s"], rows["s"]),
        }
    from langstream_tpu.models.kvquant import quantize_rows

    L = rows.shape[0]
    Kh = cache["s"].shape[3]
    q = quantize_rows(rows.reshape(L, B, T, Kh, KhD // Kh))
    return {
        "q": scatter(cache["q"], q["q"].reshape(L, B, T, KhD)),
        "s": scatter(cache["s"], q["s"]),
    }


def gather_kv(
    cache,                    # (L, nb, bs, KhD) array or int8 {"q","s"} pool
    block_tables: jax.Array,  # (B, max_blocks)
    num_read_blocks: int,     # static: table columns to read (window bucket)
):
    """XLA reference read: densify the first ``num_read_blocks`` blocks of
    every slot → ``(L, B, num_read_blocks*bs, KhD)`` (int8 pools gather
    data and scales alike — trailing dims pass through)."""
    tables = block_tables[:, :num_read_blocks]               # (B, nrb)
    B = tables.shape[0]

    def gather(pool):
        bs = pool.shape[2]
        tail = pool.shape[3:]
        gathered = jnp.take(pool, tables, axis=1)  # (L, B, nrb, bs, tail)
        return gathered.reshape(
            (pool.shape[0], B, num_read_blocks * bs) + tail
        )

    if isinstance(cache, dict):
        return jax.tree.map(gather, cache)
    return gather(cache)


# ---------------------------------------------------------------------------
# host-side block management
# ---------------------------------------------------------------------------


class BlockManager:
    """Free-list + worst-case reservation accounting (no preemption needed:
    admission only passes when the request's worst case fits, while physical
    blocks are handed out lazily as generation grows).

    Block 0 is reserved as the scatter scratch target for masked writes and
    is never allocated.

    **Automatic prefix caching** (vLLM-style): full blocks of committed
    prompts are content-addressed by a chained digest of their tokens.
    A new request whose prompt starts with a cached chain adopts those
    blocks read-only (refcounted — decode never writes below its start
    position, so sharing is safe) and prefills only the suffix. Cache-only
    blocks (refcount held just by the cache) are evicted LRU when the free
    list runs dry, so caching never reduces admissible capacity.
    """

    def __init__(self, layout: PagedLayout, slots: int):
        self.layout = layout
        self._free = list(range(layout.num_blocks - 1, 0, -1))  # block 0 reserved
        self._reserved = 0
        # adaptive pool-shrink (docs/RESILIENCE.md): blocks withheld from
        # the admission budget after a device allocator failure. Purely a
        # LOGICAL reduction — the pool arrays stay allocated; admission
        # just reserves against a smaller usable count until the engine's
        # recovery probe restores it. Floored so the largest admissible
        # request can still ever fit (a shrunk pool must degrade, never
        # deadlock the queue).
        self._budget_reduction = 0
        # tiered prefix store hook (serving/prefixstore.py): called with
        # (digest_hex, block) when pool pressure organically evicts a
        # cached prefix block WITHOUT a demotion — the tier ledgers must
        # see every byte leave, never silently
        self.on_prefix_evict = None
        # per-slot: shared (adopted, refcounted) prefix blocks + owned tail
        self._slot_shared: list[list[int]] = [[] for _ in range(slots)]
        self._slot_blocks: list[list[int]] = [[] for _ in range(slots)]
        self._slot_reservation = [0] * slots
        self.tables = np.zeros(
            (slots, layout.max_blocks_per_slot), dtype=np.int32
        )
        # prefix cache: chain digest -> block id (insertion order = LRU),
        # block refcounts (slot adoptions + cache membership), reverse map,
        # and the chain topology (parent digest + child count) so eviction
        # is leaf-first — evicting a chain HEAD would orphan its cached
        # descendants (match_prefix walks from the head and stops at the
        # first miss), leaving unreachable blocks pinned in the pool
        self._prefix: dict[bytes, int] = {}
        self._refs: dict[int, int] = {}
        self._block_digest: dict[int, bytes] = {}
        self._parent: dict[bytes, bytes] = {}
        self._nchildren: dict[bytes, int] = {}

    # -- prefix cache --------------------------------------------------

    def _digests(self, prompt_tokens):
        """Chained content digests, one per FULL block of the prompt.
        Lazy: callers that stop early (first cache miss, table bound) pay
        only for the digests they actually walk."""
        import hashlib

        bs = self.layout.block_size
        prev = b""
        for i in range(len(prompt_tokens) // bs):
            block = prompt_tokens[i * bs : (i + 1) * bs]
            h = hashlib.blake2b(digest_size=16)
            h.update(prev)
            h.update(np.asarray(block, dtype=np.int64).tobytes())
            prev = h.digest()
            yield prev

    def chain_digests(self, prompt_tokens, limit: int | None = None):
        """The prompt's chained full-block digests as a list (the lazy
        :meth:`_digests` walk, bounded). ``limit`` defaults to the same
        ``(len(prompt)-1)//block_size`` bound :meth:`match_prefix` uses —
        at least one token must prefill to produce logits. Wait-free
        beyond the hashing itself (PFX801's T0 lookup path)."""
        bs = self.layout.block_size
        if limit is None:
            limit = (len(prompt_tokens) - 1) // bs
        out: list[bytes] = []
        for i, d in enumerate(self._digests(prompt_tokens)):
            if i >= limit:
                break
            out.append(d)
        return out

    def prefix_has(self, digest: bytes) -> bool:
        """Whether the T0 cache holds a block for this chain digest."""
        return digest in self._prefix

    def match_prefix(
        self, prompt_tokens, digests=None
    ) -> tuple[list[int], int]:
        """Longest cached chain covering at most ``len(prompt)-1`` tokens
        (at least one token must prefill to produce logits). Returns
        (blocks, reused_token_count) WITHOUT claiming them — call
        :meth:`adopt_prefix` after admission. ``digests`` lets a caller
        that already walked :meth:`chain_digests` (the tiered store's
        admission path hashes the chain once and shares it) skip
        re-hashing the prompt."""
        bs = self.layout.block_size
        limit = (len(prompt_tokens) - 1) // bs
        blocks: list[int] = []
        walk = digests if digests is not None else self._digests(prompt_tokens)
        for i, d in enumerate(walk):
            if i >= limit:
                break
            b = self._prefix.get(d)
            if b is None:
                break
            blocks.append(b)
        return blocks, len(blocks) * bs

    def adopt_prefix(self, slot: int, blocks: list[int]) -> None:
        """Install shared prefix blocks at the head of a slot's table."""
        assert not self._slot_shared[slot] and not self._slot_blocks[slot]
        for i, b in enumerate(blocks):
            self._refs[b] = self._refs.get(b, 0) + 1
            self.tables[slot, i] = b
            # LRU touch
            d = self._block_digest.get(b)
            if d is not None and d in self._prefix:
                self._prefix[d] = self._prefix.pop(d)
        self._slot_shared[slot] = list(blocks)

    def register_prefix(self, slot: int, prompt_tokens) -> None:
        """After a committed prefill: publish the slot's full prompt blocks
        into the cache (first writer wins per digest)."""
        table = self._slot_shared[slot] + self._slot_blocks[slot]
        prev = b""
        for i, d in enumerate(self._digests(prompt_tokens)):
            if i >= len(table):
                break
            if d in self._prefix:
                self._prefix[d] = self._prefix.pop(d)  # LRU touch
                prev = d
                continue
            b = table[i]
            if b in self._block_digest:
                break  # block already published under another digest:
                       # deeper chain links would dangle — stop here
            self._prefix[d] = b
            self._block_digest[b] = d
            self._refs[b] = self._refs.get(b, 0) + 1
            self._parent[d] = prev
            self._nchildren.setdefault(d, 0)
            if prev:
                self._nchildren[prev] = self._nchildren.get(prev, 0) + 1
            prev = d

    def _evict_one(self) -> bool:
        """Drop the least-recently-used cache-only LEAF block (no cached
        children) to the free list — heads stay until their chains drain."""
        for d, b in list(self._prefix.items()):  # insertion order = LRU
            if self._refs.get(b, 0) != 1:  # a slot still reads it
                continue
            if self._nchildren.get(d, 0) > 0:  # interior: would orphan tail
                continue
            del self._prefix[d]
            del self._block_digest[b]
            parent = self._parent.pop(d, b"")
            self._nchildren.pop(d, None)
            if parent and parent in self._nchildren:
                self._nchildren[parent] -= 1
            self._unref(b)
            if self.on_prefix_evict is not None:
                # pool pressure dropped a cached block with no demotion:
                # the tier ledgers record the loss (serving/prefixstore.py)
                self.on_prefix_evict(d.hex(), b)
            return True
        return False

    # -- tiered prefix store surface (serving/prefixstore.py) ----------
    # Demotion picks LRU cache-only LEAF blocks (the same candidates
    # _evict_one would drop), the engine gathers their rows to host on
    # the dispatch thread, then drop_prefix() frees them; promotion
    # allocates fresh blocks via install_prefix_chain() and the engine
    # scatters the T1 rows back in. All decision paths are wait-free
    # (PFX801): dict walks and list ops, no I/O, no device syncs.

    def evictable_prefixes(
        self, max_n: int
    ) -> list[tuple[bytes, int, bytes]]:
        """Up to ``max_n`` demotion candidates, LRU-first: cache-only
        (refcount 1) leaf blocks as ``(digest, block, parent_digest)``.
        Pure read — nothing is claimed until :meth:`drop_prefix`."""
        out: list[tuple[bytes, int, bytes]] = []
        for d, b in self._prefix.items():  # insertion order = LRU
            if len(out) >= max_n:
                break
            if self._refs.get(b, 0) != 1:
                continue
            if self._nchildren.get(d, 0) > 0:
                continue
            out.append((d, b, self._parent.get(d, b"")))
        return out

    def drop_prefix(self, digest: bytes) -> int | None:
        """Targeted :meth:`_evict_one`: free ONE cached block by digest
        (cache-only leaves only — a block a slot still reads, or an
        interior chain link, refuses with ``None``). The demotion path
        calls this only AFTER the block's rows are safely on host."""
        b = self._prefix.get(digest)
        if b is None:
            return None
        if self._refs.get(b, 0) != 1:
            return None
        if self._nchildren.get(digest, 0) > 0:
            return None
        del self._prefix[digest]
        del self._block_digest[b]
        parent = self._parent.pop(digest, b"")
        self._nchildren.pop(digest, None)
        if parent and parent in self._nchildren:
            self._nchildren[parent] -= 1
        self._unref(b)
        return b

    def install_prefix_chain(
        self, chain: list[tuple[bytes, bytes]]
    ) -> list[int] | None:
        """Allocate + publish fresh cache-owned blocks for a promoted
        chain segment (``[(digest, parent_digest), ...]`` in chain
        order; the first parent must already be cached or empty). The
        engine scatters the promoted rows into the returned blocks
        before any admission adopts them. All-or-nothing: an allocation
        failure mid-chain rolls the published links back and returns
        ``None`` (the promotion falls back to cold compute)."""
        if not chain:
            return []
        first_parent = chain[0][1]
        if first_parent and first_parent not in self._prefix:
            return None  # broken linkage: would orphan the whole segment
        installed: list[tuple[bytes, int, bytes]] = []
        try:
            for digest, parent in chain:
                if digest in self._prefix:
                    # raced with a concurrent register: keep the cached
                    # block, roll back our partial segment
                    raise RuntimeError("digest already cached")
                # mark the parent interior BEFORE allocating: _alloc may
                # evict a cache-only leaf to find space, and the parent
                # must not be that leaf or the new link would orphan
                if parent:
                    self._nchildren[parent] = (
                        self._nchildren.get(parent, 0) + 1
                    )
                try:
                    b = self._alloc()  # refcount 1: cache-owned
                except RuntimeError:
                    if parent and parent in self._nchildren:
                        self._nchildren[parent] -= 1
                    raise
                self._prefix[digest] = b
                self._block_digest[b] = digest
                self._parent[digest] = parent
                self._nchildren.setdefault(digest, 0)
                installed.append((digest, b, parent))
        except RuntimeError:
            for digest, b, parent in reversed(installed):
                del self._prefix[digest]
                del self._block_digest[b]
                self._parent.pop(digest, None)
                self._nchildren.pop(digest, None)
                if parent and parent in self._nchildren:
                    self._nchildren[parent] -= 1
                self._unref(b)
            return None
        return [b for _, b, _ in installed]

    # -- refcounted block lifecycle (every live block holds ≥1 ref:
    # its owning/adopting slots and, once published, the cache) ---------

    def _alloc(self) -> int:
        if not self._free and not self._evict_one():
            raise RuntimeError(
                "paged KV pool exhausted despite reservation accounting"
            )
        b = self._free.pop()
        self._refs[b] = 1
        return b

    def _unref(self, b: int) -> None:
        n = self._refs.get(b, 0) - 1
        if n <= 0:
            self._refs.pop(b, None)
            self._free.append(b)
        else:
            self._refs[b] = n

    # -- admission -----------------------------------------------------

    def blocks_needed(self, total_tokens: int) -> int:
        return -(-total_tokens // self.layout.block_size)

    def fits_ever(self, total_tokens: int) -> bool:
        """Whether a request of this worst-case size could EVER be admitted
        (even into an empty pool) — callers must reject oversized requests
        up front or they would queue forever."""
        return self.blocks_needed(total_tokens) <= min(
            self.layout.num_blocks - 1, self.layout.max_blocks_per_slot
        )

    def can_admit(self, total_tokens: int) -> bool:
        need = self.blocks_needed(total_tokens)
        return (
            self._reserved + need <= self.usable_blocks
            and need <= self.layout.max_blocks_per_slot
        )

    # -- adaptive budget (pool-shrink, docs/RESILIENCE.md) --------------

    @property
    def configured_blocks(self) -> int:
        """The configured usable pool (block 0 is scratch)."""
        return self.layout.num_blocks - 1

    @property
    def usable_blocks(self) -> int:
        """The LIVE admission budget: configured minus withheld."""
        return self.configured_blocks - self._budget_reduction

    @property
    def budget_reduction(self) -> int:
        return self._budget_reduction

    @property
    def reserved_blocks(self) -> int:
        return self._reserved

    def _budget_floor(self) -> int:
        """Never shrink below one max-size slot's worth: requests that
        passed ``fits_ever`` must stay admissible *eventually* or they
        would queue forever under a shrink that never fully restores."""
        return min(self.layout.max_blocks_per_slot, self.configured_blocks)

    def reduce_budget(self, blocks: int) -> int:
        """Withhold up to ``blocks`` from the admission budget (clamped
        to the floor). Returns the blocks actually withheld — 0 means
        the budget is already at its floor. Existing reservations may
        transiently exceed the new budget; ``can_admit`` simply refuses
        new work until completions (or preemptions) drain them."""
        actual = max(0, min(int(blocks), self.usable_blocks - self._budget_floor()))
        self._budget_reduction += actual
        return actual

    def restore_budget(self, blocks: int | None = None) -> int:
        """Return withheld blocks to the budget (all of them when
        ``blocks`` is None). Returns the blocks actually restored."""
        actual = (
            self._budget_reduction
            if blocks is None
            else max(0, min(int(blocks), self._budget_reduction))
        )
        self._budget_reduction -= actual
        return actual

    def admit(self, slot: int, total_tokens: int) -> None:
        need = self.blocks_needed(total_tokens)
        if not self.can_admit(total_tokens):
            raise RuntimeError("paged KV pool exhausted (admission bug)")
        self._slot_reservation[slot] = need
        self._reserved += need

    # -- growth --------------------------------------------------------

    def ensure_capacity(self, slot: int, tokens: int) -> int:
        """Allocate physical blocks so ``tokens`` positions fit. Returns
        the number of blocks allocated (0 = table unchanged; truthy
        exactly when it changed, so boolean callers keep working — and
        the pool-grow flight events can carry block/byte counts).

        Growth is capped at the slot's admission reservation: speculative
        decode chunks may request coverage past the request's true maximum,
        and capping keeps the reservation invariant (those excess writes are
        redirected to the scratch block by the unallocated table columns).
        """
        need = self.blocks_needed(tokens)
        if self._slot_reservation[slot]:
            need = min(need, self._slot_reservation[slot])
        grown = 0
        while len(self._slot_shared[slot]) + len(self._slot_blocks[slot]) < need:
            b = self._alloc()
            idx = len(self._slot_shared[slot]) + len(self._slot_blocks[slot])
            self._slot_blocks[slot].append(b)
            self.tables[slot, idx] = b
            grown += 1
        return grown

    def release(self, slot: int) -> None:
        for b in self._slot_shared[slot] + self._slot_blocks[slot]:
            self._unref(b)
        self._reserved -= self._slot_reservation[slot]
        self._slot_reservation[slot] = 0
        self._slot_shared[slot] = []
        self._slot_blocks[slot] = []
        self.tables[slot, :] = 0

    # -- stats ---------------------------------------------------------

    def used_ratio(self) -> float:
        """Admission-relevant pool pressure: the RESERVED fraction of the
        usable pool (block 0 is scratch). Admission gates on worst-case
        reservations, so a pool can refuse admissions while mostly
        unallocated — an allocated-fullness gauge would read near empty
        exactly when ``no-kv-blocks`` stalls fire. Physical allocation
        (free/live/cached) lives in :meth:`stats`. Cheap enough for the
        flight recorder to sample per burst. Measured against the LIVE
        budget: a shrunk pool reports the pressure admissions actually
        face, not the configured capacity they temporarily lost."""
        usable = self.usable_blocks
        return self._reserved / usable if usable > 0 else 1.0

    def prefix_block_count(self) -> int:
        """Blocks currently pinned by the content-addressed prefix cache
        — a single GIL-atomic ``len``, so the attribution memory ledger
        can read it wait-free from any thread (OBS505)."""
        return len(self._prefix)

    def stats(self) -> dict:
        return {
            "num_blocks": self.layout.num_blocks,
            "free_blocks": len(self._free),
            "reserved_blocks": self._reserved,
            # adaptive pool-shrink posture: the live admission budget vs
            # what the config sized (withheld > 0 = shrunk right now)
            "budget_blocks": self.usable_blocks,
            "withheld_blocks": self._budget_reduction,
            # distinct physical blocks: shared prefix blocks adopted by
            # several slots count once (live + free + cache-only ≤ usable)
            "live_blocks": len(
                {
                    b
                    for s, o in zip(self._slot_shared, self._slot_blocks)
                    for b in (*s, *o)
                }
            ),
            "cached_prefix_blocks": len(self._prefix),
        }
