"""Paged KV cache: block-table layout + pure read/write/attention helpers.

Why paged: the dense cache ``(L, slots, S, Kh, D)`` reserves
``slots × max_seq_len`` rows of HBM up front, so slot count is capped by the
*worst-case* sequence length even when every live request is short. Paging
(vLLM-style) slices the cache into fixed ``block_size``-row blocks shared
from one pool; a slot holds ``ceil(len/bs)`` blocks, mapped by a small
host-managed block table. Capacity then scales with *actual* tokens
resident, not slots × S (reference parity: SURVEY §7 build-order item 6).

TPU-first layout: the pool is ``(L, num_blocks, block_size, Kh*D)`` — the
trailing two dims ``(block_size, Kh*D)`` are clean (8,128)-multiples, so
both XLA scatters/gathers and the Pallas kernel DMA whole tiles. All
functions here are jit-pure; the host side (free lists, reservations) lives
in :class:`BlockManager`.

Read paths:
- :func:`gather_kv` — XLA reference: gathers a slot's blocks into a dense
  window. Correct everywhere (CPU tests, sharded meshes); costs an extra
  HBM round-trip for the gathered copy.
- :mod:`langstream_tpu.ops.paged_attention` — Pallas kernel that walks the
  block table directly via scalar prefetch; no gathered copy. Single-chip
  TPU fast path.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class PagedLayout:
    """Static geometry of the paged pool."""

    block_size: int
    num_blocks: int
    max_blocks_per_slot: int

    @classmethod
    def for_model(
        cls,
        max_seq_len: int,
        slots: int,
        block_size: int = 64,
        hbm_fraction_of_dense: float = 0.5,
        num_blocks: int | None = None,
    ) -> "PagedLayout":
        """Size the pool to ``hbm_fraction_of_dense`` of what the dense
        cache would reserve (the whole point: same slot count, less HBM —
        or more slots at the same HBM)."""
        max_blocks_per_slot = -(-max_seq_len // block_size)
        if num_blocks is None:
            dense_rows = slots * max_seq_len
            num_blocks = max(
                slots + 1, int(dense_rows * hbm_fraction_of_dense) // block_size
            )
        return cls(
            block_size=block_size,
            num_blocks=num_blocks,
            max_blocks_per_slot=max_blocks_per_slot,
        )


def init_paged_kv_cache(
    config, layout: PagedLayout
) -> tuple[jax.Array, jax.Array]:
    """Pool arrays ``(L, num_blocks, block_size, Kh*D)`` for K and V."""
    c = config
    shape = (
        c.layers,
        layout.num_blocks,
        layout.block_size,
        c.kv_heads * c.head_dim,
    )
    return jnp.zeros(shape, dtype=c.dtype), jnp.zeros(shape, dtype=c.dtype)


def paged_cache_spec(mesh_axes: tuple[str, ...]):
    """Pool (L, nb, bs, Kh*D): the trailing fused head axis shards on tp.
    Blocks are NOT sharded on dp (any slot may use any block), so paged
    serving shards the model, not the pool rows."""
    from jax.sharding import PartitionSpec as P

    tp = "tp" if "tp" in mesh_axes else None
    return P(None, None, None, tp)


# ---------------------------------------------------------------------------
# jit-pure read/write
# ---------------------------------------------------------------------------


def write_rows(
    cache: jax.Array,       # (L, nb, bs, KhD)
    rows: jax.Array,        # (L, B, T, KhD) — new K or V rows per slot
    block_tables: jax.Array,  # (B, max_blocks) int32
    starts: jax.Array,      # (B,) first sequence position of rows[;, b]
    valid: jax.Array,       # (B, T) bool — rows beyond a slot's true count
) -> jax.Array:
    """Scatter ``rows`` into the pool at each slot's block-mapped positions.

    Invalid rows are redirected to a scratch row (block 0 never backs live
    data; see BlockManager) so the scatter stays shape-static.
    """
    L, nb, bs, KhD = cache.shape
    B, T = rows.shape[1], rows.shape[2]
    pos = starts[:, None] + jnp.arange(T)[None, :]          # (B, T)
    # clamp: invalid rows may compute positions past the table; they're
    # redirected to scratch below, the clamp just keeps indexing in-bounds
    block_idx = jnp.clip(pos // bs, 0, block_tables.shape[1] - 1)
    offset = pos % bs
    blocks = jnp.take_along_axis(block_tables, block_idx, axis=1)  # (B, T)
    flat = blocks * bs + offset                              # row in (nb*bs)
    # invalid rows land in block 0 (reserved scratch, never allocated), so
    # the scatter stays shape-static and garbage never touches live data
    flat = jnp.where(valid, flat, 0).reshape(-1)             # (B*T,)
    flat_rows = rows.reshape(L, B * T, KhD)
    flat_cache = cache.reshape(L, nb * bs, KhD)
    updated = flat_cache.at[:, flat].set(flat_rows)
    return updated.reshape(L, nb, bs, KhD)


def gather_kv(
    cache: jax.Array,         # (L, nb, bs, KhD)
    block_tables: jax.Array,  # (B, max_blocks)
    num_read_blocks: int,     # static: table columns to read (window bucket)
) -> jax.Array:
    """XLA reference read: densify the first ``num_read_blocks`` blocks of
    every slot → ``(L, B, num_read_blocks*bs, KhD)``."""
    L, nb, bs, KhD = cache.shape
    tables = block_tables[:, :num_read_blocks]               # (B, nrb)
    gathered = jnp.take(cache, tables, axis=1)               # (L, B, nrb, bs, KhD)
    B = tables.shape[0]
    return gathered.reshape(L, B, num_read_blocks * bs, KhD)


# ---------------------------------------------------------------------------
# host-side block management
# ---------------------------------------------------------------------------


class BlockManager:
    """Free-list + worst-case reservation accounting (no preemption needed:
    admission only passes when the request's worst case fits, while physical
    blocks are handed out lazily as generation grows).

    Block 0 is reserved as the scatter scratch target for masked writes and
    is never allocated.
    """

    def __init__(self, layout: PagedLayout, slots: int):
        self.layout = layout
        self._free = list(range(layout.num_blocks - 1, 0, -1))  # block 0 reserved
        self._reserved = 0
        self._slot_blocks: list[list[int]] = [[] for _ in range(slots)]
        self._slot_reservation = [0] * slots
        self.tables = np.zeros(
            (slots, layout.max_blocks_per_slot), dtype=np.int32
        )

    # -- admission -----------------------------------------------------

    def blocks_needed(self, total_tokens: int) -> int:
        return -(-total_tokens // self.layout.block_size)

    def fits_ever(self, total_tokens: int) -> bool:
        """Whether a request of this worst-case size could EVER be admitted
        (even into an empty pool) — callers must reject oversized requests
        up front or they would queue forever."""
        return self.blocks_needed(total_tokens) <= min(
            self.layout.num_blocks - 1, self.layout.max_blocks_per_slot
        )

    def can_admit(self, total_tokens: int) -> bool:
        need = self.blocks_needed(total_tokens)
        usable = self.layout.num_blocks - 1  # block 0 is scratch
        return (
            self._reserved + need <= usable
            and need <= self.layout.max_blocks_per_slot
        )

    def admit(self, slot: int, total_tokens: int) -> None:
        need = self.blocks_needed(total_tokens)
        if not self.can_admit(total_tokens):
            raise RuntimeError("paged KV pool exhausted (admission bug)")
        self._slot_reservation[slot] = need
        self._reserved += need

    # -- growth --------------------------------------------------------

    def ensure_capacity(self, slot: int, tokens: int) -> bool:
        """Allocate physical blocks so ``tokens`` positions fit. Returns
        True if the table changed.

        Growth is capped at the slot's admission reservation: speculative
        decode chunks may request coverage past the request's true maximum,
        and capping keeps the reservation invariant (those excess writes are
        redirected to the scratch block by the unallocated table columns).
        """
        need = self.blocks_needed(tokens)
        if self._slot_reservation[slot]:
            need = min(need, self._slot_reservation[slot])
        changed = False
        while len(self._slot_blocks[slot]) < need:
            if not self._free:
                raise RuntimeError(
                    "paged KV pool exhausted despite reservation accounting"
                )
            b = self._free.pop()
            idx = len(self._slot_blocks[slot])
            self._slot_blocks[slot].append(b)
            self.tables[slot, idx] = b
            changed = True
        return changed

    def release(self, slot: int) -> None:
        blocks = self._slot_blocks[slot]
        self._free.extend(reversed(blocks))
        self._reserved -= self._slot_reservation[slot]
        self._slot_reservation[slot] = 0
        self._slot_blocks[slot] = []
        self.tables[slot, :] = 0

    # -- stats ---------------------------------------------------------

    def stats(self) -> dict:
        return {
            "num_blocks": self.layout.num_blocks,
            "free_blocks": len(self._free),
            "reserved_blocks": self._reserved,
            "live_blocks": sum(len(b) for b in self._slot_blocks),
        }
