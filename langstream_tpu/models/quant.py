"""Weight-only int8 quantization for the serving path.

TPU rationale: single-chip decode is weight/cache HBM-read bound; storing
weights as int8 with per-output-channel f32 scales halves the weight bytes
per step. The dequant (``convert int8→bf16`` + one broadcast multiply) sits
directly on the matmul operand so XLA fuses it into the dot's operand load —
no materialized bf16 copy of the weights.

Scope: serving inference only, single-chip or TP-sharded (scales shard with
their weights via :func:`quantize_specs`). Quality: per-channel symmetric
int8 on weights only (activations stay bf16) — the standard recipe that is
lossless in practice for decoder LMs of this size.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """int8 weight + f32 scale, shaped to broadcast on dequant.

    ``dtype`` (static aux data) is the pre-quantization dtype the weight
    dequantizes back to, so quantized and plain params are interchangeable
    in the same jitted model code.
    """

    q: jax.Array  # int8, original shape
    s: jax.Array  # f32, reduced to 1 along the contraction axis
    dtype: Any = jnp.bfloat16

    def tree_flatten(self):
        return (self.q, self.s), self.dtype

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    @property
    def shape(self):
        return self.q.shape


def as_weight(t):
    """Dequantize a QTensor (or pass a plain array through). Call at the
    matmul site so the convert fuses into the dot's operand load."""
    if isinstance(t, QTensor):
        return t.q.astype(t.dtype) * t.s.astype(t.dtype)
    return t


def embedding_take(embed, tokens):
    """Row gather that understands quantized embeddings (gathers int8 rows
    and their per-row scales, dequantizes only the gathered rows)."""
    if isinstance(embed, QTensor):
        rows = jnp.take(embed.q, tokens, axis=0).astype(embed.dtype)
        scales = jnp.take(embed.s, tokens, axis=0).astype(embed.dtype)
        return rows * scales
    return jnp.take(embed, tokens, axis=0)


def quantize_tensor(w: jax.Array, axis: int) -> QTensor:
    """Symmetric per-channel int8: scale reduces over ``axis`` (the
    contraction dimension of the matmul that consumes ``w``)."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, s=scale, dtype=w.dtype)


def quantize_specs(specs: Any, params: Any) -> Any:
    """Lift a PartitionSpec tree over a (partially) quantized param tree.

    Each QTensor leaf's spec ``P`` becomes ``QTensor(q=P, s=P')`` where
    ``P'`` drops the mesh axis on dimensions the scale reduces to size 1
    (a size-1 dimension cannot shard over a >1 mesh axis; the scale is
    simply replicated along the contraction axis, which is exactly the
    axis TP row-sharding splits). Column-sharded weights keep the axis:
    their scales are per-output-channel and shard with the outputs.
    """
    from jax.sharding import PartitionSpec as P

    def lift(p, w):
        if not isinstance(w, QTensor):
            return p
        ndim = w.q.ndim
        entries = list(p) + [None] * (ndim - len(list(p)))
        s_entries = [
            None if w.s.shape[i] == 1 else entries[i] for i in range(ndim)
        ]
        return QTensor(q=p, s=P(*s_entries), dtype=w.dtype)

    return jax.tree.map(
        lift, specs, params,
        is_leaf=lambda x: isinstance(x, (P, QTensor)),
    )


def quantize_llama_params(params: dict) -> dict:
    """Quantize every matmul weight of a Llama param tree; norms stay bf16.

    Contraction axes: projections contract the middle (hidden/intermediate)
    axis of their stacked (L, in, out) layout; embed is gathered per row;
    lm_head contracts hidden.
    """
    layers = params["layers"]
    return {
        "embed": quantize_tensor(params["embed"], axis=1),      # per row
        "layers": {
            "attn_norm": layers["attn_norm"],
            "wq": quantize_tensor(layers["wq"], axis=1),
            "wk": quantize_tensor(layers["wk"], axis=1),
            "wv": quantize_tensor(layers["wv"], axis=1),
            "wo": quantize_tensor(layers["wo"], axis=1),
            "mlp_norm": layers["mlp_norm"],
            "w_gate": quantize_tensor(layers["w_gate"], axis=1),
            "w_up": quantize_tensor(layers["w_up"], axis=1),
            "w_down": quantize_tensor(layers["w_down"], axis=1),
        },
        "final_norm": params["final_norm"],
        "lm_head": quantize_tensor(params["lm_head"], axis=0),
    }


def quantize_moe_params(params: dict) -> dict:
    """MoE twin of :func:`quantize_llama_params`: attention/embed/lm_head as
    the dense model; expert weights per-(layer, expert, output-channel); the
    router stays float32 (tiny, and routing decisions are numerically
    delicate — see ``init_moe_params``)."""
    layers = params["layers"]
    return {
        "embed": quantize_tensor(params["embed"], axis=1),
        "layers": {
            "attn_norm": layers["attn_norm"],
            "wq": quantize_tensor(layers["wq"], axis=1),
            "wk": quantize_tensor(layers["wk"], axis=1),
            "wv": quantize_tensor(layers["wv"], axis=1),
            "wo": quantize_tensor(layers["wo"], axis=1),
            "mlp_norm": layers["mlp_norm"],
            "router": layers["router"],
            # (L, E, H, I) contract H; (L, E, I, H) contract I
            "w_gate": quantize_tensor(layers["w_gate"], axis=2),
            "w_up": quantize_tensor(layers["w_up"], axis=2),
            "w_down": quantize_tensor(layers["w_down"], axis=2),
        },
        "final_norm": params["final_norm"],
        "lm_head": quantize_tensor(params["lm_head"], axis=0),
    }
