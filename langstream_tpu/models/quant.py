"""Weight-only int8 quantization for the serving path.

TPU rationale: single-chip decode is weight/cache HBM-read bound; storing
weights as int8 with per-output-channel f32 scales halves the weight bytes
per step. The dequant (``convert int8→bf16`` + one broadcast multiply) sits
directly on the matmul operand so XLA fuses it into the dot's operand load —
no materialized bf16 copy of the weights.

Scope: serving inference only, single-chip or TP-sharded (scales shard with
their weights via :func:`quantize_specs`). Quality: per-channel symmetric
int8 on weights only (activations stay bf16) — the standard recipe that is
lossless in practice for decoder LMs of this size.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """int8 weight + f32 scale, shaped to broadcast on dequant.

    ``dtype`` (static aux data) is the pre-quantization dtype the weight
    dequantizes back to, so quantized and plain params are interchangeable
    in the same jitted model code.
    """

    q: jax.Array  # int8, original shape
    s: jax.Array  # f32, reduced to 1 along the contraction axis
    dtype: Any = jnp.bfloat16

    def tree_flatten(self):
        return (self.q, self.s), self.dtype

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    @property
    def shape(self):
        return self.q.shape


def as_weight(t):
    """Dequantize a QTensor (or pass a plain array through). Call at the
    matmul site so the convert fuses into the dot's operand load."""
    if isinstance(t, QTensor):
        return t.q.astype(t.dtype) * t.s.astype(t.dtype)
    return t


def embedding_take(embed, tokens):
    """Row gather that understands quantized embeddings (gathers int8 rows
    and their per-row scales, dequantizes only the gathered rows)."""
    if isinstance(embed, QTensor):
        rows = jnp.take(embed.q, tokens, axis=0).astype(embed.dtype)
        scales = jnp.take(embed.s, tokens, axis=0).astype(embed.dtype)
        return rows * scales
    return jnp.take(embed, tokens, axis=0)


def quantize_tensor(w: jax.Array, axis: int) -> QTensor:
    """Symmetric per-channel int8: scale reduces over ``axis`` (the
    contraction dimension of the matmul that consumes ``w``)."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, s=scale, dtype=w.dtype)


def quantize_specs(specs: Any, params: Any) -> Any:
    """Lift a PartitionSpec tree over a (partially) quantized param tree.

    Each QTensor leaf's spec ``P`` becomes ``QTensor(q=P, s=P')`` where
    ``P'`` drops the mesh axis on dimensions the scale reduces to size 1
    (a size-1 dimension cannot shard over a >1 mesh axis; the scale is
    simply replicated along the contraction axis, which is exactly the
    axis TP row-sharding splits). Column-sharded weights keep the axis:
    their scales are per-output-channel and shard with the outputs.
    """
    from jax.sharding import PartitionSpec as P

    def lift(p, w):
        if not isinstance(w, QTensor):
            return p
        ndim = w.q.ndim
        entries = list(p) + [None] * (ndim - len(list(p)))
        s_entries = [
            None if w.s.shape[i] == 1 else entries[i] for i in range(ndim)
        ]
        return QTensor(q=p, s=P(*s_entries), dtype=w.dtype)

    return jax.tree.map(
        lift, specs, params,
        is_leaf=lambda x: isinstance(x, (P, QTensor)),
    )


def quantize_llama_params(params: dict) -> dict:
    """Quantize every matmul weight of a Llama param tree; norms stay bf16.

    Contraction axes: projections contract the middle (hidden/intermediate)
    axis of their stacked (L, in, out) layout; embed is gathered per row;
    lm_head contracts hidden.
    """
    layers = params["layers"]
    return {
        "embed": quantize_tensor(params["embed"], axis=1),      # per row
        "layers": {
            "attn_norm": layers["attn_norm"],
            "wq": quantize_tensor(layers["wq"], axis=1),
            "wk": quantize_tensor(layers["wk"], axis=1),
            "wv": quantize_tensor(layers["wv"], axis=1),
            "wo": quantize_tensor(layers["wo"], axis=1),
            "mlp_norm": layers["mlp_norm"],
            "w_gate": quantize_tensor(layers["w_gate"], axis=1),
            "w_up": quantize_tensor(layers["w_up"], axis=1),
            "w_down": quantize_tensor(layers["w_down"], axis=1),
        },
        "final_norm": params["final_norm"],
        "lm_head": quantize_tensor(params["lm_head"], axis=0),
    }


# ---------------------------------------------------------------------------
# direct quantized random-init (never materializes the full-precision tree)
# ---------------------------------------------------------------------------


def _q8_normal(key, lead: int, shape: tuple, fan_in: int, axis: int):
    """``(lead, *shape)`` random-normal weights generated DIRECTLY as int8
    values + per-channel f32 scales, one ``shape``-sized f32 transient at a
    time (``lax.map`` = sequential scan — XLA allocates a single chunk's
    f32 buffer, quantizes it, and reuses it for the next chunk).

    ``axis`` is the contraction axis WITHIN ``shape`` (the scale reduces it
    to 1, matching :func:`quantize_tensor`'s keepdims layout).
    """
    scale = 1.0 / math.sqrt(fan_in)

    def one(k):
        w = jax.random.normal(k, shape, dtype=jnp.float32) * scale
        amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
        s = jnp.where(amax > 0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
        return q, s

    return jax.lax.map(one, jax.random.split(key, lead))


def _chunks(n: int, target: int = 32) -> int:
    """Largest chunk count <= target that divides n (vocab chunking)."""
    for d in range(min(target, n), 0, -1):
        if n % d == 0:
            return d
    return 1


def _q8_embed(key, vocab: int, hidden: int, dtype):
    """(vocab, hidden) embedding, per-ROW int8, generated in row chunks."""
    nb = _chunks(vocab)
    q, s = _q8_normal(key, nb, (vocab // nb, hidden), hidden, 1)
    return QTensor(
        q=q.reshape(vocab, hidden), s=s.reshape(vocab, 1), dtype=dtype
    )


def _q8_lm_head(key, hidden: int, vocab: int, dtype):
    """(hidden, vocab) head, per-COLUMN int8 (contracts hidden), generated
    in column chunks."""
    nb = _chunks(vocab)
    q, s = _q8_normal(key, nb, (hidden, vocab // nb), hidden, 0)
    return QTensor(
        q=jnp.moveaxis(q, 0, 1).reshape(hidden, vocab),
        s=jnp.moveaxis(s, 0, 1).reshape(1, vocab),
        dtype=dtype,
    )


def init_llama_params_q8(config, key: jax.Array | None = None) -> dict:
    """Random-init Llama params ALREADY weight-quantized — the serving
    engine's offline/dev init for ``quantize: int8`` postures.

    Same tree/shapes/dtypes/scale-layout as
    ``quantize_llama_params(init_llama_params(c))`` (identical bytes and
    FLOPs at run time), but peak memory during init is the int8 tree plus
    ONE layer's f32 transient (~in*out*4 bytes), never the full bf16/f32
    tree. At the Llama-3-8B shape that is ~8 GB + 235 MB instead of the
    >= 24 GB init→quantize peak that OOM'd a 16 GB v5e chip (round-4
    benchmark root cause).

    ``config`` is duck-typed (LlamaConfig fields only — vocab_size, hidden,
    layers, heads, kv_heads, head_dim, intermediate, dtype).
    """
    key = key if key is not None else jax.random.PRNGKey(0)
    c = config
    keys = jax.random.split(key, 10)
    qkv_dim = c.heads * c.head_dim
    kv_dim = c.kv_heads * c.head_dim
    L = c.layers

    def stacked(k, rows, cols, fan_in):
        # per-layer (rows, cols), contraction axis 0 — stacked (L, rows,
        # cols) with scales (L, 1, cols), exactly quantize_tensor(axis=1)
        q, s = _q8_normal(k, L, (rows, cols), fan_in, 0)
        return QTensor(q=q, s=s, dtype=c.dtype)

    return {
        "embed": _q8_embed(keys[0], c.vocab_size, c.hidden, c.dtype),
        "layers": {
            "attn_norm": jnp.ones((L, c.hidden), dtype=c.dtype),
            "wq": stacked(keys[1], c.hidden, qkv_dim, c.hidden),
            "wk": stacked(keys[2], c.hidden, kv_dim, c.hidden),
            "wv": stacked(keys[3], c.hidden, kv_dim, c.hidden),
            "wo": stacked(keys[4], qkv_dim, c.hidden, qkv_dim),
            "mlp_norm": jnp.ones((L, c.hidden), dtype=c.dtype),
            "w_gate": stacked(keys[5], c.hidden, c.intermediate, c.hidden),
            "w_up": stacked(keys[6], c.hidden, c.intermediate, c.hidden),
            "w_down": stacked(keys[7], c.intermediate, c.hidden, c.intermediate),
        },
        "final_norm": jnp.ones((c.hidden,), dtype=c.dtype),
        "lm_head": _q8_lm_head(keys[8], c.hidden, c.vocab_size, c.dtype),
    }


def init_moe_params_q8(config, key: jax.Array | None = None) -> dict:
    """MoE twin of :func:`init_llama_params_q8`: expert weights generated
    per-(layer, expert) — a Mixtral-8x7B expert tensor is (32, 8, 4096,
    14336); the chunked init's f32 transient is one (4096, 14336) slice
    (235 MB), not the 60 GB stacked tensor. Router stays float32 exactly as
    ``init_moe_params`` (tiny, numerically delicate)."""
    key = key if key is not None else jax.random.PRNGKey(0)
    c = config
    keys = jax.random.split(key, 12)
    qkv_dim = c.heads * c.head_dim
    kv_dim = c.kv_heads * c.head_dim
    L, E, I = c.layers, c.experts, c.moe_intermediate

    def stacked(k, rows, cols, fan_in):
        q, s = _q8_normal(k, L, (rows, cols), fan_in, 0)
        return QTensor(q=q, s=s, dtype=c.dtype)

    def expert(k, rows, cols, fan_in):
        # (L*E) chunks of (rows, cols) → (L, E, rows, cols) with scales
        # (L, E, 1, cols): quantize_tensor(axis=2)'s layout
        q, s = _q8_normal(k, L * E, (rows, cols), fan_in, 0)
        return QTensor(
            q=q.reshape(L, E, rows, cols),
            s=s.reshape(L, E, 1, cols),
            dtype=c.dtype,
        )

    return {
        "embed": _q8_embed(keys[0], c.vocab_size, c.hidden, c.dtype),
        "layers": {
            "attn_norm": jnp.ones((L, c.hidden), dtype=c.dtype),
            "wq": stacked(keys[1], c.hidden, qkv_dim, c.hidden),
            "wk": stacked(keys[2], c.hidden, kv_dim, c.hidden),
            "wv": stacked(keys[3], c.hidden, kv_dim, c.hidden),
            "wo": stacked(keys[4], qkv_dim, c.hidden, qkv_dim),
            "mlp_norm": jnp.ones((L, c.hidden), dtype=c.dtype),
            "router": jax.random.normal(
                keys[5], (L, c.hidden, E), dtype=jnp.float32
            ) * (1.0 / math.sqrt(c.hidden)),
            "w_gate": expert(keys[6], c.hidden, I, c.hidden),
            "w_up": expert(keys[7], c.hidden, I, c.hidden),
            "w_down": expert(keys[8], I, c.hidden, I),
        },
        "final_norm": jnp.ones((c.hidden,), dtype=c.dtype),
        "lm_head": _q8_lm_head(keys[9], c.hidden, c.vocab_size, c.dtype),
    }


def quantize_moe_params(params: dict) -> dict:
    """MoE twin of :func:`quantize_llama_params`: attention/embed/lm_head as
    the dense model; expert weights per-(layer, expert, output-channel); the
    router stays float32 (tiny, and routing decisions are numerically
    delicate — see ``init_moe_params``)."""
    layers = params["layers"]
    return {
        "embed": quantize_tensor(params["embed"], axis=1),
        "layers": {
            "attn_norm": layers["attn_norm"],
            "wq": quantize_tensor(layers["wq"], axis=1),
            "wk": quantize_tensor(layers["wk"], axis=1),
            "wv": quantize_tensor(layers["wv"], axis=1),
            "wo": quantize_tensor(layers["wo"], axis=1),
            "mlp_norm": layers["mlp_norm"],
            "router": layers["router"],
            # (L, E, H, I) contract H; (L, E, I, H) contract I
            "w_gate": quantize_tensor(layers["w_gate"], axis=2),
            "w_up": quantize_tensor(layers["w_up"], axis=2),
            "w_down": quantize_tensor(layers["w_down"], axis=2),
        },
        "final_norm": params["final_norm"],
        "lm_head": quantize_tensor(params["lm_head"], axis=0),
    }
