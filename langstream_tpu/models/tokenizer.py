"""Tokenizers.

Real deployments load a HuggingFace tokenizer (``transformers`` is in the
image; tokenizer files must be local — no network egress). The first-party
fallback is a deterministic byte-level tokenizer: ids 0..255 are raw bytes
plus BOS/EOS/PAD specials — always available, reversible, and sufficient for
the serving engine, tests, and benchmarks (a token is a token to the MXU).
"""

from __future__ import annotations

from typing import Protocol


class Tokenizer(Protocol):
    bos_id: int
    eos_id: int
    pad_id: int
    vocab_size: int

    def encode(self, text: str) -> list[int]: ...

    def decode(self, ids: list[int]) -> str: ...


class ByteTokenizer:
    """Byte-level: token id = byte value; specials above 255."""

    def __init__(self) -> None:
        self.pad_id = 256
        self.bos_id = 257
        self.eos_id = 258
        self.vocab_size = 259

    def encode(self, text: str) -> list[int]:
        return [self.bos_id] + list(text.encode("utf-8"))

    def decode(self, ids: list[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")


class HFTokenizer:
    """Wrapper over a local HuggingFace tokenizer directory."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.bos_id = self._tok.bos_token_id or 0
        self.eos_id = self._tok.eos_token_id or 0
        self.pad_id = self._tok.pad_token_id or self.eos_id
        self.vocab_size = self._tok.vocab_size

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text)

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def load_tokenizer(spec: str | None) -> Tokenizer:
    """``None``/``"byte"`` → ByteTokenizer; otherwise a local HF path."""
    if spec in (None, "byte", "bytes"):
        return ByteTokenizer()
    return HFTokenizer(spec)
