"""Native components: build + process management for the tpustream broker.

The C++ broker (``tsbroker.cc``) is compiled on demand with the system
toolchain and cached next to the source; a content hash keyed cache makes
rebuilds automatic when the source changes.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import tempfile
from pathlib import Path

_HERE = Path(__file__).resolve().parent
BROKER_SOURCE = _HERE / "tsbroker.cc"
_BIN_DIR = _HERE / "bin"


class NativeBuildError(RuntimeError):
    pass


def toolchain_available() -> bool:
    return shutil.which("g++") is not None


def ensure_broker_binary() -> Path:
    """Compile (or reuse a cached) tsbroker binary; returns its path."""
    source = BROKER_SOURCE.read_bytes()
    digest = hashlib.sha256(source).hexdigest()[:16]
    binary = _BIN_DIR / f"tsbroker-{digest}"
    if binary.exists():
        return binary
    if not toolchain_available():
        raise NativeBuildError("g++ not found; cannot build tsbroker")
    _BIN_DIR.mkdir(parents=True, exist_ok=True)
    # Build to a temp name then rename: concurrent builders race benignly.
    fd, tmp = tempfile.mkstemp(prefix="tsbroker-", dir=_BIN_DIR)
    os.close(fd)
    try:
        proc = subprocess.run(
            ["g++", "-std=c++17", "-O2", "-o", tmp, str(BROKER_SOURCE)],
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            raise NativeBuildError(f"tsbroker build failed:\n{proc.stderr}")
        os.chmod(tmp, 0o755)
        os.replace(tmp, binary)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    # Prune stale cached builds.
    for old in _BIN_DIR.glob("tsbroker-*"):
        if old != binary:
            try:
                old.unlink()
            except OSError:
                pass
    return binary


class BrokerProcess:
    """Launches a tsbroker subprocess and reports its port.

    Used by the dev-mode runner (the reference's embedded Kafka/Kraft in the
    runtime-tester image, ``langstream-runtime-tester/src/main/docker/
    Dockerfile:23-40``) and by tests.
    """

    def __init__(self, port: int = 0, data_dir: str | None = None,
                 host: str = "127.0.0.1"):
        self.host = host
        self._requested_port = port
        self.data_dir = data_dir
        self.port: int | None = None
        self.proc: subprocess.Popen | None = None

    def start(self) -> "BrokerProcess":
        binary = ensure_broker_binary()
        cmd = [str(binary), "--host", self.host, "--port",
               str(self._requested_port)]
        if self.data_dir:
            cmd += ["--data-dir", self.data_dir]
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True
        )
        line = self.proc.stdout.readline().strip()
        if not line.startswith("LISTENING "):
            self.stop()
            raise NativeBuildError(f"tsbroker failed to start: {line!r}")
        self.port = int(line.split()[1])
        return self

    def stop(self) -> None:
        if self.proc is not None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
            self.proc = None

    def __enter__(self) -> "BrokerProcess":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
