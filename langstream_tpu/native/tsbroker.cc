// tpustream broker — the framework's native inter-agent transport.
//
// Role parity: the reference's messaging substrate + Kafka runtime semantics
// (partitioned logs, consumer groups with rebalance, committed offsets,
// long-poll fetch, dead-letter topics created on demand by clients):
//   langstream-kafka-runtime/src/main/java/ai/langstream/kafka/runner/
//     KafkaConsumerWrapper.java:41,203 (group rebalance, contiguous commits)
//   KafkaTopicConnectionsRuntime.java:74,112,123
// The reference delegates this to an external Kafka cluster; here it is an
// in-tree, dependency-free C++17 single-threaded epoll reactor so agent pods
// have a broker wherever they run (dev laptop, CI, TPU host). Records ride
// DCN between agents; ICI collectives inside the serving agent are JAX/XLA's
// job, not this broker's.
//
// Wire protocol (all integers big-endian):
//   frame   := u32 payload_len, payload
//   request := u8 opcode, u64 request_id, body
//   reply   := u64 request_id, u8 status, body
//   str     := u16 len, bytes          (utf-8, topics/groups/clients)
//   blob    := u32 len, bytes          (record keys/values/header values)
// Statuses: 0 OK, 1 ERROR(str msg), 2 REBALANCED (consumer must re-join).
//
// Opcodes:
//   1 PRODUCE   topic, key:blob, value:blob, nheaders:u16, {str,blob}*
//               -> partition:u32, offset:u64
//   2 FETCH     topic, partition:u32, offset:u64, max_records:u32,
//               max_wait_ms:u32, group, generation:u32
//               -> nrecords:u32, {offset:u64, key, value, nheaders,{str,blob}*}*
//   3 COMMIT    group, topic, partition:u32, offset:u64     -> (empty)
//   4 COMMITTED group, topic, partition:u32                 -> offset:i64 (-1 none)
//   5 CREATE_TOPIC topic, partitions:u32                    -> (empty; idempotent)
//   6 DELETE_TOPIC topic                                    -> (empty)
//   7 LIST_TOPICS                                           -> n:u32, {topic, partitions:u32}*
//   8 JOIN_GROUP  group, topic, client_id
//               -> generation:u32, nparts:u32, partition:u32*
//   9 LEAVE_GROUP group, topic, client_id                   -> (empty)
//  10 PING                                                  -> (empty)
//  11 OFFSETS   topic, partition:u32                        -> earliest:u64, end:u64
//
// Persistence (optional --data-dir): append-only per-partition record log
// (replayed on boot) + append-only committed-offsets log (compacted on boot).

#include <arpa/inet.h>
#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <deque>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

uint64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             Clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// Buffer codec

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  explicit Reader(const std::string& s)
      : p(reinterpret_cast<const uint8_t*>(s.data())),
        end(p + s.size()) {}

  bool need(size_t n) {
    if (static_cast<size_t>(end - p) < n) {
      fail = true;
      return false;
    }
    return true;
  }
  uint8_t u8() {
    if (!need(1)) return 0;
    return *p++;
  }
  uint16_t u16() {
    if (!need(2)) return 0;
    uint16_t v = (uint16_t(p[0]) << 8) | p[1];
    p += 2;
    return v;
  }
  uint32_t u32() {
    if (!need(4)) return 0;
    uint32_t v = (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
                 (uint32_t(p[2]) << 8) | p[3];
    p += 4;
    return v;
  }
  uint64_t u64() {
    uint64_t hi = u32();
    uint64_t lo = u32();
    return (hi << 32) | lo;
  }
  std::string str() {
    uint16_t n = u16();
    if (!need(n)) return {};
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }
  std::string blob() {
    uint32_t n = u32();
    if (!need(n)) return {};
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }
};

struct Writer {
  std::string out;

  void u8(uint8_t v) { out.push_back(char(v)); }
  void u16(uint16_t v) {
    out.push_back(char(v >> 8));
    out.push_back(char(v));
  }
  void u32(uint32_t v) {
    out.push_back(char(v >> 24));
    out.push_back(char(v >> 16));
    out.push_back(char(v >> 8));
    out.push_back(char(v));
  }
  void u64(uint64_t v) {
    u32(uint32_t(v >> 32));
    u32(uint32_t(v));
  }
  void str(const std::string& s) {
    u16(uint16_t(s.size()));
    out += s;
  }
  void blob(const std::string& s) {
    u32(uint32_t(s.size()));
    out += s;
  }
};

// ---------------------------------------------------------------------------
// Log storage

struct RecordEntry {
  uint64_t offset;
  std::string key;
  std::string value;
  std::vector<std::pair<std::string, std::string>> headers;
};

struct Partition {
  std::deque<RecordEntry> log;
  uint64_t base = 0;  // offset of log.front()
  FILE* file = nullptr;

  uint64_t end_offset() const { return base + log.size(); }
};

struct Topic {
  std::string name;
  std::vector<Partition> parts;
  uint64_t round_robin = 0;
};

// Consumer-group state is per (group, topic): membership drives partition
// assignment; committed offsets survive membership churn (and restarts when
// --data-dir is set) — parity with Kafka consumer-group + __consumer_offsets.
struct GroupTopic {
  uint32_t generation = 0;
  std::vector<std::string> members;                       // client ids, sorted
  std::map<std::string, std::vector<uint32_t>> assigned;  // client -> parts
  std::map<uint32_t, int64_t> committed;                  // part -> next offset

  void rebalance(uint32_t nparts) {
    generation++;
    assigned.clear();
    if (members.empty()) return;
    for (uint32_t p = 0; p < nparts; p++) {
      assigned[members[p % members.size()]].push_back(p);
    }
  }
};

// ---------------------------------------------------------------------------
// Connections & parked fetches

struct ParkedFetch {
  int conn_fd;
  uint64_t request_id;
  std::string topic;
  uint32_t partition;
  uint64_t offset;
  uint32_t max_records;
  uint64_t deadline_ms;
  std::string group;
  uint32_t generation;
};

struct Conn {
  int fd;
  std::string inbuf;
  std::string outbuf;
  // group memberships held by this connection: (group, topic) -> client_id.
  std::map<std::pair<std::string, std::string>, std::string> memberships;
  bool closed = false;
};

class Broker {
 public:
  Broker(std::string data_dir) : data_dir_(std::move(data_dir)) {}

  int run(const char* host, int port);

 private:
  std::string data_dir_;
  std::unordered_map<std::string, Topic> topics_;
  std::map<std::pair<std::string, std::string>, GroupTopic> groups_;
  std::unordered_map<int, std::unique_ptr<Conn>> conns_;
  std::vector<ParkedFetch> parked_;
  FILE* offsets_file_ = nullptr;
  int epfd_ = -1;

  // --- persistence -------------------------------------------------------
  std::string part_path(const std::string& topic, uint32_t p) const {
    return data_dir_ + "/" + topic + "." + std::to_string(p) + ".log";
  }

  void load_state();
  void open_part_file(const std::string& tname, uint32_t pi, Partition& part);
  void persist_record(Partition& part, const RecordEntry& r);
  void persist_offset(const std::string& group, const std::string& topic,
                      uint32_t part, int64_t offset);

  // --- topic ops ---------------------------------------------------------
  Topic& ensure_topic(const std::string& name, uint32_t partitions);

  // --- request handling --------------------------------------------------
  void handle_frame(Conn& c, const std::string& payload);
  void reply_ok(Conn& c, uint64_t rid, const std::string& body);
  void reply_err(Conn& c, uint64_t rid, const std::string& msg);
  void reply_status(Conn& c, uint64_t rid, uint8_t status);
  void send_frame(Conn& c, const std::string& payload);

  std::string encode_records(const Partition& part, uint64_t offset,
                             uint32_t max_records, uint32_t* count);
  void try_wake_parked(const std::string& topic, uint32_t partition);
  void expire_parked(uint64_t now);
  int next_parked_timeout(uint64_t now);

  void drop_conn(int fd);
  void flush_out(Conn& c);
  void update_epoll(Conn& c);
};

void Broker::load_state() {
  if (data_dir_.empty()) return;
  mkdir(data_dir_.c_str(), 0755);
  // Replay committed offsets (compacting: last write wins).
  std::string opath = data_dir_ + "/offsets.log";
  if (FILE* f = fopen(opath.c_str(), "rb")) {
    std::string content;
    char buf[65536];
    size_t n;
    while ((n = fread(buf, 1, sizeof buf, f)) > 0) content.append(buf, n);
    fclose(f);
    Reader r(content);
    while (!r.fail && r.p < r.end) {
      std::string group = r.str();
      std::string topic = r.str();
      uint32_t part = r.u32();
      int64_t off = int64_t(r.u64());
      if (r.fail) break;  // torn tail write
      groups_[{group, topic}].committed[part] = off;
    }
  }
  offsets_file_ = fopen(opath.c_str(), "ab");
  // Replay record logs: files named <topic>.<partition>.log. Topics are
  // re-created with partition count = max index + 1.
  std::map<std::string, uint32_t> seen;  // topic -> nparts
  if (DIR* d = opendir(data_dir_.c_str())) {
    while (dirent* e = readdir(d)) {
      std::string fn = e->d_name;
      size_t dot2 = fn.rfind(".log");
      if (dot2 == std::string::npos || dot2 + 4 != fn.size()) continue;
      size_t dot1 = fn.rfind('.', dot2 - 1);
      if (dot1 == std::string::npos) continue;
      std::string tname = fn.substr(0, dot1);
      if (tname == "offsets") continue;
      uint32_t pi = uint32_t(atoi(fn.substr(dot1 + 1, dot2 - dot1 - 1).c_str()));
      auto& n = seen[tname];
      n = std::max(n, pi + 1);
    }
    closedir(d);
  }
  for (auto& [tname, nparts] : seen) {
    Topic& t = topics_[tname];
    t.name = tname;
    t.parts.resize(nparts);
    for (uint32_t pi = 0; pi < nparts; pi++) {
      std::string content;
      if (FILE* f = fopen(part_path(tname, pi).c_str(), "rb")) {
        char buf[65536];
        size_t n;
        while ((n = fread(buf, 1, sizeof buf, f)) > 0) content.append(buf, n);
        fclose(f);
      }
      Reader r(content);
      Partition& part = t.parts[pi];
      while (!r.fail && r.p < r.end) {
        RecordEntry rec;
        rec.offset = r.u64();
        rec.key = r.blob();
        rec.value = r.blob();
        uint16_t nh = r.u16();
        for (uint16_t h = 0; h < nh && !r.fail; h++) {
          std::string hk = r.str();
          rec.headers.emplace_back(hk, r.blob());
        }
        if (r.fail) break;
        if (part.log.empty()) part.base = rec.offset;
        part.log.push_back(std::move(rec));
      }
      open_part_file(tname, pi, part);
    }
  }
}

void Broker::open_part_file(const std::string& tname, uint32_t pi,
                            Partition& part) {
  if (data_dir_.empty()) return;
  part.file = fopen(part_path(tname, pi).c_str(), "ab");
}

void Broker::persist_record(Partition& part, const RecordEntry& r) {
  if (!part.file) return;
  Writer w;
  w.u64(r.offset);
  w.blob(r.key);
  w.blob(r.value);
  w.u16(uint16_t(r.headers.size()));
  for (auto& [hk, hv] : r.headers) {
    w.str(hk);
    w.blob(hv);
  }
  fwrite(w.out.data(), 1, w.out.size(), part.file);
  fflush(part.file);
}

void Broker::persist_offset(const std::string& group, const std::string& topic,
                            uint32_t part, int64_t offset) {
  if (!offsets_file_) return;
  Writer w;
  w.str(group);
  w.str(topic);
  w.u32(part);
  w.u64(uint64_t(offset));
  fwrite(w.out.data(), 1, w.out.size(), offsets_file_);
  fflush(offsets_file_);
}

Topic& Broker::ensure_topic(const std::string& name, uint32_t partitions) {
  auto it = topics_.find(name);
  if (it != topics_.end()) return it->second;
  Topic& t = topics_[name];
  t.name = name;
  t.parts.resize(std::max(1u, partitions));
  for (uint32_t pi = 0; pi < t.parts.size(); pi++) {
    open_part_file(name, pi, t.parts[pi]);
  }
  return t;
}

std::string Broker::encode_records(const Partition& part, uint64_t offset,
                                   uint32_t max_records, uint32_t* count) {
  Writer w;
  uint64_t start = std::max(offset, part.base);
  uint32_t n = 0;
  for (uint64_t o = start; o < part.end_offset() && n < max_records; o++, n++) {
    const RecordEntry& r = part.log[o - part.base];
    w.u64(r.offset);
    w.blob(r.key);
    w.blob(r.value);
    w.u16(uint16_t(r.headers.size()));
    for (auto& [hk, hv] : r.headers) {
      w.str(hk);
      w.blob(hv);
    }
  }
  *count = n;
  return w.out;
}

void Broker::send_frame(Conn& c, const std::string& payload) {
  if (c.closed) return;
  char hdr[4] = {char(payload.size() >> 24), char(payload.size() >> 16),
                 char(payload.size() >> 8), char(payload.size())};
  c.outbuf.append(hdr, 4);
  c.outbuf += payload;
  flush_out(c);
}

void Broker::reply_ok(Conn& c, uint64_t rid, const std::string& body) {
  Writer w;
  w.u64(rid);
  w.u8(0);
  w.out += body;
  send_frame(c, w.out);
}

void Broker::reply_err(Conn& c, uint64_t rid, const std::string& msg) {
  Writer w;
  w.u64(rid);
  w.u8(1);
  w.str(msg);
  send_frame(c, w.out);
}

void Broker::reply_status(Conn& c, uint64_t rid, uint8_t status) {
  Writer w;
  w.u64(rid);
  w.u8(status);
  send_frame(c, w.out);
}

void Broker::try_wake_parked(const std::string& topic, uint32_t partition) {
  for (size_t i = 0; i < parked_.size();) {
    ParkedFetch& pf = parked_[i];
    if (pf.topic != topic || pf.partition != partition) {
      i++;
      continue;
    }
    auto cit = conns_.find(pf.conn_fd);
    if (cit == conns_.end()) {
      parked_.erase(parked_.begin() + i);
      continue;
    }
    Topic& t = topics_[topic];
    Partition& part = t.parts[partition];
    uint32_t count = 0;
    std::string recs = encode_records(part, pf.offset, pf.max_records, &count);
    if (count == 0) {
      i++;
      continue;
    }
    Writer w;
    w.u32(count);
    w.out += recs;
    reply_ok(*cit->second, pf.request_id, w.out);
    parked_.erase(parked_.begin() + i);
  }
}

void Broker::expire_parked(uint64_t now) {
  for (size_t i = 0; i < parked_.size();) {
    if (parked_[i].deadline_ms > now) {
      i++;
      continue;
    }
    auto cit = conns_.find(parked_[i].conn_fd);
    if (cit != conns_.end()) {
      Writer w;
      w.u32(0);
      reply_ok(*cit->second, parked_[i].request_id, w.out);
    }
    parked_.erase(parked_.begin() + i);
  }
}

int Broker::next_parked_timeout(uint64_t now) {
  if (parked_.empty()) return 1000;
  uint64_t best = UINT64_MAX;
  for (auto& pf : parked_) best = std::min(best, pf.deadline_ms);
  if (best <= now) return 0;
  return int(std::min<uint64_t>(best - now, 1000));
}

void Broker::handle_frame(Conn& c, const std::string& payload) {
  Reader r(payload);
  uint8_t op = r.u8();
  uint64_t rid = r.u64();
  if (r.fail) return;

  switch (op) {
    case 1: {  // PRODUCE
      std::string tname = r.str();
      RecordEntry rec;
      rec.key = r.blob();
      rec.value = r.blob();
      uint16_t nh = r.u16();
      for (uint16_t h = 0; h < nh && !r.fail; h++) {
        std::string hk = r.str();
        rec.headers.emplace_back(hk, r.blob());
      }
      if (r.fail) return reply_err(c, rid, "bad produce");
      Topic& t = ensure_topic(tname, 1);
      uint32_t pi;
      if (!rec.key.empty()) {
        // FNV-1a over key — stable partition routing for keyed records.
        uint64_t h = 1469598103934665603ull;
        for (unsigned char ch : rec.key) h = (h ^ ch) * 1099511628211ull;
        pi = uint32_t(h % t.parts.size());
      } else {
        pi = uint32_t(t.round_robin++ % t.parts.size());
      }
      Partition& part = t.parts[pi];
      rec.offset = part.end_offset();
      persist_record(part, rec);
      part.log.push_back(std::move(rec));
      Writer w;
      w.u32(pi);
      w.u64(part.log.back().offset);
      reply_ok(c, rid, w.out);
      try_wake_parked(tname, pi);
      break;
    }
    case 2: {  // FETCH
      std::string tname = r.str();
      uint32_t pi = r.u32();
      uint64_t offset = r.u64();
      uint32_t maxr = r.u32();
      uint32_t wait_ms = r.u32();
      std::string group = r.str();
      uint32_t generation = r.u32();
      if (r.fail) return reply_err(c, rid, "bad fetch");
      auto tit = topics_.find(tname);
      if (tit == topics_.end() || pi >= tit->second.parts.size()) {
        return reply_err(c, rid, "unknown topic/partition " + tname);
      }
      if (!group.empty()) {
        auto git = groups_.find({group, tname});
        if (git == groups_.end() || git->second.generation != generation) {
          return reply_status(c, rid, 2);  // REBALANCED
        }
      }
      Partition& part = tit->second.parts[pi];
      uint32_t count = 0;
      std::string recs = encode_records(part, offset, maxr, &count);
      if (count == 0 && wait_ms > 0) {
        parked_.push_back({c.fd, rid, tname, pi, offset, maxr,
                           now_ms() + wait_ms, group, generation});
        break;
      }
      Writer w;
      w.u32(count);
      w.out += recs;
      reply_ok(c, rid, w.out);
      break;
    }
    case 3: {  // COMMIT
      std::string group = r.str();
      std::string tname = r.str();
      uint32_t pi = r.u32();
      uint64_t off = r.u64();
      if (r.fail) return reply_err(c, rid, "bad commit");
      groups_[{group, tname}].committed[pi] = int64_t(off);
      persist_offset(group, tname, pi, int64_t(off));
      reply_ok(c, rid, "");
      break;
    }
    case 4: {  // COMMITTED
      std::string group = r.str();
      std::string tname = r.str();
      uint32_t pi = r.u32();
      if (r.fail) return reply_err(c, rid, "bad committed");
      int64_t off = -1;
      auto git = groups_.find({group, tname});
      if (git != groups_.end()) {
        auto oit = git->second.committed.find(pi);
        if (oit != git->second.committed.end()) off = oit->second;
      }
      Writer w;
      w.u64(uint64_t(off));
      reply_ok(c, rid, w.out);
      break;
    }
    case 5: {  // CREATE_TOPIC
      std::string tname = r.str();
      uint32_t nparts = r.u32();
      if (r.fail) return reply_err(c, rid, "bad create");
      ensure_topic(tname, nparts);
      reply_ok(c, rid, "");
      break;
    }
    case 6: {  // DELETE_TOPIC
      std::string tname = r.str();
      if (r.fail) return reply_err(c, rid, "bad delete");
      auto tit = topics_.find(tname);
      if (tit != topics_.end()) {
        for (uint32_t pi = 0; pi < tit->second.parts.size(); pi++) {
          if (tit->second.parts[pi].file) fclose(tit->second.parts[pi].file);
          if (!data_dir_.empty()) unlink(part_path(tname, pi).c_str());
        }
        topics_.erase(tit);
      }
      reply_ok(c, rid, "");
      break;
    }
    case 7: {  // LIST_TOPICS
      Writer w;
      w.u32(uint32_t(topics_.size()));
      for (auto& [name, t] : topics_) {
        w.str(name);
        w.u32(uint32_t(t.parts.size()));
      }
      reply_ok(c, rid, w.out);
      break;
    }
    case 8: {  // JOIN_GROUP
      std::string group = r.str();
      std::string tname = r.str();
      std::string client = r.str();
      if (r.fail) return reply_err(c, rid, "bad join");
      Topic& t = ensure_topic(tname, 1);
      GroupTopic& g = groups_[{group, tname}];
      // Re-joins from existing members (e.g. after observing REBALANCED)
      // must NOT bump the generation, or members would invalidate each
      // other forever.
      if (std::find(g.members.begin(), g.members.end(), client) ==
          g.members.end()) {
        g.members.push_back(client);
        std::sort(g.members.begin(), g.members.end());
        g.rebalance(uint32_t(t.parts.size()));
      } else if (g.generation == 0) {
        g.rebalance(uint32_t(t.parts.size()));
      }
      c.memberships[{group, tname}] = client;
      Writer w;
      w.u32(g.generation);
      auto& mine = g.assigned[client];
      w.u32(uint32_t(mine.size()));
      for (uint32_t p : mine) w.u32(p);
      reply_ok(c, rid, w.out);
      break;
    }
    case 9: {  // LEAVE_GROUP
      std::string group = r.str();
      std::string tname = r.str();
      std::string client = r.str();
      if (r.fail) return reply_err(c, rid, "bad leave");
      auto git = groups_.find({group, tname});
      if (git != groups_.end()) {
        auto& g = git->second;
        g.members.erase(std::remove(g.members.begin(), g.members.end(), client),
                        g.members.end());
        auto tit = topics_.find(tname);
        g.rebalance(tit == topics_.end()
                        ? 0
                        : uint32_t(tit->second.parts.size()));
      }
      c.memberships.erase({group, tname});
      reply_ok(c, rid, "");
      break;
    }
    case 10: {  // PING
      reply_ok(c, rid, "");
      break;
    }
    case 11: {  // OFFSETS
      std::string tname = r.str();
      uint32_t pi = r.u32();
      if (r.fail) return reply_err(c, rid, "bad offsets");
      auto tit = topics_.find(tname);
      if (tit == topics_.end() || pi >= tit->second.parts.size()) {
        Writer w;
        w.u64(0);
        w.u64(0);
        reply_ok(c, rid, w.out);
        break;
      }
      Partition& part = tit->second.parts[pi];
      Writer w;
      w.u64(part.base);
      w.u64(part.end_offset());
      reply_ok(c, rid, w.out);
      break;
    }
    default:
      reply_err(c, rid, "unknown opcode");
  }
}

void Broker::drop_conn(int fd) {
  auto it = conns_.find(fd);
  if (it == conns_.end()) return;
  // Leaving all groups this connection held triggers rebalances so other
  // members pick up the orphaned partitions (parity: session-timeout
  // rebalance in the Kafka group protocol).
  for (auto& [gt, client] : it->second->memberships) {
    auto git = groups_.find(gt);
    if (git == groups_.end()) continue;
    auto& g = git->second;
    g.members.erase(std::remove(g.members.begin(), g.members.end(), client),
                    g.members.end());
    auto tit = topics_.find(gt.second);
    g.rebalance(tit == topics_.end() ? 0
                                     : uint32_t(tit->second.parts.size()));
  }
  for (size_t i = 0; i < parked_.size();) {
    if (parked_[i].conn_fd == fd) {
      parked_.erase(parked_.begin() + i);
    } else {
      i++;
    }
  }
  epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
  close(fd);
  conns_.erase(it);
}

void Broker::flush_out(Conn& c) {
  while (!c.outbuf.empty()) {
    ssize_t n = ::send(c.fd, c.outbuf.data(), c.outbuf.size(), MSG_NOSIGNAL);
    if (n > 0) {
      c.outbuf.erase(0, size_t(n));
    } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      break;
    } else {
      c.closed = true;
      break;
    }
  }
  update_epoll(c);
}

void Broker::update_epoll(Conn& c) {
  epoll_event ev{};
  ev.data.fd = c.fd;
  ev.events = EPOLLIN | (c.outbuf.empty() ? 0u : uint32_t(EPOLLOUT));
  epoll_ctl(epfd_, EPOLL_CTL_MOD, c.fd, &ev);
}

int Broker::run(const char* host, int port) {
  signal(SIGPIPE, SIG_IGN);
  load_state();

  int lfd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  inet_pton(AF_INET, host, &addr.sin_addr);
  if (bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0) {
    perror("bind");
    return 1;
  }
  socklen_t alen = sizeof addr;
  getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
  listen(lfd, 128);

  printf("LISTENING %d\n", ntohs(addr.sin_port));
  fflush(stdout);

  epfd_ = epoll_create1(0);
  epoll_event ev{};
  ev.data.fd = lfd;
  ev.events = EPOLLIN;
  epoll_ctl(epfd_, EPOLL_CTL_ADD, lfd, &ev);

  std::vector<epoll_event> events(256);
  for (;;) {
    uint64_t now = now_ms();
    expire_parked(now);
    int nev = epoll_wait(epfd_, events.data(), int(events.size()),
                         next_parked_timeout(now));
    if (nev < 0) {
      if (errno == EINTR) continue;
      perror("epoll_wait");
      return 1;
    }
    for (int i = 0; i < nev; i++) {
      int fd = events[i].data.fd;
      if (fd == lfd) {
        for (;;) {
          int cfd = accept4(lfd, nullptr, nullptr, SOCK_NONBLOCK);
          if (cfd < 0) break;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          auto conn = std::make_unique<Conn>();
          conn->fd = cfd;
          epoll_event cev{};
          cev.data.fd = cfd;
          cev.events = EPOLLIN;
          epoll_ctl(epfd_, EPOLL_CTL_ADD, cfd, &cev);
          conns_[cfd] = std::move(conn);
        }
        continue;
      }
      auto cit = conns_.find(fd);
      if (cit == conns_.end()) continue;
      Conn& c = *cit->second;
      if (events[i].events & (EPOLLHUP | EPOLLERR)) {
        drop_conn(fd);
        continue;
      }
      if (events[i].events & EPOLLOUT) flush_out(c);
      if (events[i].events & EPOLLIN) {
        char buf[65536];
        bool closed = false;
        for (;;) {
          ssize_t n = recv(fd, buf, sizeof buf, 0);
          if (n > 0) {
            c.inbuf.append(buf, size_t(n));
          } else if (n == 0) {
            closed = true;
            break;
          } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
            break;
          } else {
            closed = true;
            break;
          }
        }
        // Drain complete frames.
        while (c.inbuf.size() >= 4) {
          uint32_t len = (uint32_t(uint8_t(c.inbuf[0])) << 24) |
                         (uint32_t(uint8_t(c.inbuf[1])) << 16) |
                         (uint32_t(uint8_t(c.inbuf[2])) << 8) |
                         uint32_t(uint8_t(c.inbuf[3]));
          if (len > (64u << 20)) {
            closed = true;
            break;
          }
          if (c.inbuf.size() < 4 + size_t(len)) break;
          std::string payload = c.inbuf.substr(4, len);
          c.inbuf.erase(0, 4 + size_t(len));
          handle_frame(c, payload);
          if (c.closed) {
            closed = true;
            break;
          }
        }
        if (closed || c.closed) drop_conn(fd);
      }
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  const char* host = "127.0.0.1";
  int port = 0;
  std::string data_dir;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    if (a == "--host" && i + 1 < argc) host = argv[++i];
    else if (a == "--port" && i + 1 < argc) port = atoi(argv[++i]);
    else if (a == "--data-dir" && i + 1 < argc) data_dir = argv[++i];
    else {
      fprintf(stderr,
              "usage: tsbroker [--host H] [--port P] [--data-dir DIR]\n");
      return 2;
    }
  }
  Broker broker(data_dir);
  return broker.run(host, port);
}
