"""Pallas TPU kernels for the serving hot ops.

- :mod:`langstream_tpu.ops.flash_attention` — blocked causal GQA attention
  (prefill/forward): O(S) memory instead of the O(S²) score matrix.

Kernels run compiled on TPU and in interpret mode on CPU (tests).
"""

from langstream_tpu.ops.flash_attention import flash_attention  # noqa: F401
