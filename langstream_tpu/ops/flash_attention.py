"""Blocked (flash) causal GQA attention as a Pallas TPU kernel.

Why a kernel: the einsum attention path materialises the full
``(B, heads, S, S)`` float32 score matrix in HBM — at S=4k, B=8, 32 heads
that is >16 GB of traffic per layer. This kernel streams K/V blocks through
VMEM with an online-softmax accumulator, so HBM traffic is O(S·D) and the
MXU sees back-to-back 128×128 tiles.

Scope: inference prefill / forward (no custom VJP — the training paths keep
the differentiable einsum attention). Causal masking only: for right-padded
self-attention batches, causality alone already hides the padded keys from
every real query row, so no per-row length input is needed (the engine
discards logits of padded rows).

Grid: ``(B, heads, num_q_blocks, num_k_blocks)`` with the K dimension
innermost; the running max / sum / accumulator live in VMEM scratch across
the K sweep and the output block is written on the last K step. Fully-masked
K blocks (``k_start > q_end``) are skipped via ``pl.when``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from langstream_tpu.jax_compat import pallas_compiler_params as _compiler_params

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _flash_kernel(
    q_ref,      # (1, 1, block_q, D)
    k_ref,      # (1, 1, block_k, D)
    v_ref,      # (1, 1, block_k, D)
    o_ref,      # (1, 1, block_q, D)
    m_ref,      # VMEM (block_q, 128) f32 — running max (broadcast cols)
    l_ref,      # VMEM (block_q, 128) f32 — running sum
    acc_ref,    # VMEM (block_q, D) f32
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    kv_len: int,
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1  # block not fully in the future

    @pl.when(run)
    def _accumulate():
        q = q_ref[0, 0]  # (block_q, D)
        k = k_ref[0, 0]  # (block_k, D)
        v = v_ref[0, 0]
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # (block_q, block_k)
        rows = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        # kv_len bound hides right-padding from non-causal queries; the
        # causal mask subsumes it for self-attention but is cheap to keep
        mask = cols < kv_len
        if causal:
            mask = mask & (rows >= cols)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[:, 0]                       # (block_q,)
        l_prev = l_ref[:, 0]
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        shift = jnp.where(m_new <= NEG_INF, 0.0, m_new)  # NaN guard
        p = jnp.exp(s - shift[:, None])
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(jnp.where(m_prev <= NEG_INF, NEG_INF, m_prev - shift))
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(ki == num_k - 1)
    def _finalize():
        l = l_ref[:, 0]
        inv = jnp.where(l > 0.0, 1.0 / jnp.maximum(l, 1e-30), 0.0)
        o_ref[0, 0] = (acc_ref[:] * inv[:, None]).astype(o_ref.dtype)


def _flash_bhsd(
    q: jax.Array,  # (B, H, Sq, D)
    k: jax.Array,  # (B, Kh, Sk, D)
    v: jax.Array,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    kv_len: int,
    interpret: bool,
) -> jax.Array:
    B, H, Sq, D = q.shape
    Kh, Sk = k.shape[1], k.shape[2]
    group = H // Kh
    grid = (B, H, pl.cdiv(Sq, block_q), pl.cdiv(Sk, block_k))
    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        kv_len=kv_len,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, D),
                lambda b, h, qi, ki: (b, h, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k, D),
                lambda b, h, qi, ki, g=group: (b, h // g, ki, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k, D),
                lambda b, h, qi, ki, g=group: (b, h // g, ki, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, D),
            lambda b, h, qi, ki: (b, h, qi, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=_compiler_params()(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)


def flash_attention(
    q: jax.Array,  # (B, Sq, H, D)
    k: jax.Array,  # (B, Sk, Kh, D)
    v: jax.Array,  # (B, Sk, Kh, D)
    *,
    causal: bool = True,
    scale: float | None = None,
    # 512-blocks measured ~2.2x faster than XLA dense attention at S=8k on
    # v5e (and never slower down to S=1k); both clamp to the sequence length
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
    mesh=None,  # jax.sharding.Mesh: run the kernel per-shard via shard_map
) -> jax.Array:
    """Flash attention over ``(batch, seq, heads, head_dim)`` tensors.

    GQA: ``H`` may be a multiple of ``Kh``. Sequences are padded up to the
    block size internally (causal masking keeps padded keys invisible to
    real queries in the self-attention case ``Sq == Sk``).

    Under a ``mesh``, ``pallas_call`` has no SPMD partitioning rule, so the
    call is wrapped in ``shard_map`` with heads on the ``tp`` axis — each
    device runs the kernel on its own head shard (attention is
    embarrassingly parallel over heads; GQA group structure is preserved
    because Q heads and KV heads shard by the same factor).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if mesh is not None:
        from functools import partial as _partial

        from jax.sharding import PartitionSpec as P

        from langstream_tpu.jax_compat import shard_map

        axes = mesh.axis_names
        H_, Kh_, B_ = q.shape[2], k.shape[2], q.shape[0]
        tp = (
            "tp"
            if "tp" in axes and mesh.shape["tp"] > 1
            and H_ % mesh.shape["tp"] == 0 and Kh_ % mesh.shape["tp"] == 0
            else None
        )
        dp = (
            "dp"
            if "dp" in axes and mesh.shape["dp"] > 1
            and B_ % mesh.shape["dp"] == 0
            else None
        )
        if tp is not None or dp is not None:
            spec = P(dp, None, tp, None)
            inner = _partial(
                flash_attention,
                causal=causal, scale=scale, block_q=block_q, block_k=block_k,
                interpret=interpret, mesh=None,
            )
            return shard_map(
                inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_rep=False,
            )(q, k, v)
        # no shardable axis (tiny batch on a dp-only mesh): the plain call
        # below is replicated per device by pjit — correct, just not sharded
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    if causal and Sq != Sk:
        raise ValueError(
            f"causal flash attention expects self-attention (Sq == Sk), got "
            f"{Sq} vs {Sk}"
        )
    block_q = min(block_q, max(16, Sq))
    block_k = min(block_k, max(16, Sk))
    pad_q = (-Sq) % block_q
    pad_k = (-Sk) % block_k
    qt = jnp.transpose(q, (0, 2, 1, 3))  # (B, H, Sq, D)
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    out = _flash_bhsd(
        qt, kt, vt,
        scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=Sk, interpret=interpret,
    )
    if pad_q:
        out = out[:, :, :Sq]
    return jnp.transpose(out, (0, 2, 1, 3))
