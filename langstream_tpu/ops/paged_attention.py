"""Paged-attention decode kernel (Pallas TPU).

One decode step reads each slot's KV *blocks* straight out of the shared
pool — the block table rides in as a scalar-prefetch argument, so each grid
step's ``index_map`` picks the right pool block to DMA into VMEM. No
densified gather copy (the XLA reference path :func:`gather_kv` pays one),
no ``slots × max_seq`` layout anywhere.

Online softmax over the block sweep, same discipline as
``flash_attention.py``. The kernel returns *partial* results
``(acc, m, l)`` — unnormalised accumulator, running max, running sum-exp —
because decode attends over two segments: the paged cache (here) and the
in-chunk KV buffer (tiny, handled in XLA). The caller merges the two with
the standard online-softmax combine (``merge_partial_attention``).

Shapes (one layer; the layer loop lives in the model's ``lax.scan``):
  q             (B, H, D)
  k_pool/v_pool (nb, bs, Kh*D)
  block_tables  (B, max_blocks) int32   [scalar prefetch]
  lengths       (B,) int32              [scalar prefetch]
  → acc (B, H, D) f32, m (B, H, 128) f32, l (B, H, 128) f32
    (m/l broadcast along a 128-lane axis: TPU-friendly layout)

Grid ``(B, num_read_blocks)``, block sweep innermost; fully-masked blocks
(``start >= length``) are skipped with ``pl.when`` — their DMA still
happens (block 0, the scratch block), which is the price of a static grid.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from langstream_tpu.jax_compat import pallas_compiler_params as _compiler_params

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _paged_kernel(
    # NOTE: _paged_mq_kernel below is this kernel's multi-query twin
    # (this one is its t_block=1 special case). They are kept separate ON
    # PURPOSE for now: this kernel is the recorded decode benchmark's hot
    # path, validated on real hardware, and consolidating the two must be
    # done with the device microbenchmark in hand (round-4 item) — not
    # blind. Any fix to the online-softmax discipline here must be
    # mirrored there until they merge.
    tables_ref,   # SMEM (B, max_blocks) int32
    lengths_ref,  # SMEM (B,) int32
    q_ref,        # (1, H, D)
    k_ref,        # (1, bs, KhD)
    v_ref,        # (1, bs, KhD)
    acc_out,      # (1, H, D) f32
    m_out,        # (1, H, 128) f32
    l_out,        # (1, H, 128) f32
    m_ref,        # VMEM (H, 128) f32
    l_ref,        # VMEM (H, 128) f32
    acc_ref,      # VMEM (H, D) f32
    *,
    scale: float,
    block_size: int,
    kv_heads: int,
    head_dim: int,
):
    b = pl.program_id(0)
    ji = pl.program_id(1)
    num_j = pl.num_programs(1)
    length = lengths_ref[b]
    start = ji * block_size

    @pl.when(ji == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(start < length)
    def _accumulate():
        H, D = acc_ref.shape
        G = H // kv_heads
        q = q_ref[0]                                   # (H, D)
        k = k_ref[0].reshape(block_size, kv_heads, head_dim)
        v = v_ref[0].reshape(block_size, kv_heads, head_dim)
        # scores per kv-head group: q rows [kh*G:(kh+1)*G] attend k[:, kh]
        qg = q.reshape(kv_heads, G, D)
        s = jax.lax.dot_general(
            qg, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * scale                                      # (Kh, G, bs)
        s = s.reshape(H, block_size)
        cols = start + jax.lax.broadcasted_iota(
            jnp.int32, (H, block_size), 1
        )
        mask = cols < length
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[:, 0]                           # (H,)
        l_prev = l_ref[:, 0]
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        shift = jnp.where(m_new <= NEG_INF, 0.0, m_new)
        p = jnp.exp(s - shift[:, None])
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(jnp.where(m_prev <= NEG_INF, NEG_INF, m_prev - shift))
        l_ref[:] = jnp.broadcast_to(
            (l_prev * alpha + jnp.sum(p, axis=1))[:, None], l_ref.shape
        )
        pg = p.reshape(kv_heads, G, block_size)
        pv = jax.lax.dot_general(
            pg.astype(v.dtype), v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )                                              # (Kh, G, D)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + pv.reshape(H, D)
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)

    @pl.when(ji == num_j - 1)
    def _finalize():
        acc_out[0] = acc_ref[:]
        m_out[0] = m_ref[:]
        l_out[0] = l_ref[:]


def _paged_kernel_q8(
    # int8 twin of _paged_kernel: k/v arrive as int8 blocks with per-(row,
    # kv-head) f32 scales. The k scale multiplies the SCORE (constant along
    # D, factored out of the dot); the v scale folds into the probabilities
    # before the value dot — exactly the fused-dequant discipline of the
    # XLA path (models/kvquant.py cache_scores/cache_values), so the two
    # lanes are numerically interchangeable.
    tables_ref,   # SMEM (B, max_blocks) int32
    lengths_ref,  # SMEM (B,) int32
    q_ref,        # (1, H, D)
    k_ref,        # (1, bs, KhD) int8
    ks_ref,       # (1, bs, Kh) f32
    v_ref,        # (1, bs, KhD) int8
    vs_ref,       # (1, bs, Kh) f32
    acc_out,      # (1, H, D) f32
    m_out,        # (1, H, 128) f32
    l_out,        # (1, H, 128) f32
    m_ref,        # VMEM (H, 128) f32
    l_ref,        # VMEM (H, 128) f32
    acc_ref,      # VMEM (H, D) f32
    *,
    scale: float,
    block_size: int,
    kv_heads: int,
    head_dim: int,
):
    b = pl.program_id(0)
    ji = pl.program_id(1)
    num_j = pl.num_programs(1)
    length = lengths_ref[b]
    start = ji * block_size

    @pl.when(ji == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(start < length)
    def _accumulate():
        H, D = acc_ref.shape
        G = H // kv_heads
        q = q_ref[0]                                   # (H, D) bf16
        ks = ks_ref[0]                                 # (bs, Kh) f32
        vs = vs_ref[0]
        # batch-LEADING discipline, transpose-free: the r5 chip attribution
        # pinned the q8 lane's 62-vs-42 ms/step loss on the per-block
        # (bs, Kh, D) → (Kh, bs, D) relayouts of BOTH operands, not the
        # gather. Unrolling the (static, small) kv-head axis turns each dot
        # into a plain 2D matmul over a contiguous lane slice of the int8
        # block — no batch dims at all, so Mosaic's "int8-converted operand
        # must carry the batch dim leading" constraint is vacuous and the
        # int8 rows stream into the MXU in their stored layout.
        s_heads = []
        for kh in range(kv_heads):
            k_h = k_ref[0][:, kh * head_dim:(kh + 1) * head_dim]  # (bs, D)
            s_h = jax.lax.dot_general(
                q[kh * G:(kh + 1) * G], k_h.astype(q.dtype),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                          # (G, bs)
            # dequant k: the scale is constant along D — apply to the score
            s_heads.append(s_h * ks[:, kh][None, :] * scale)
        s = jnp.concatenate(s_heads, axis=0)           # (H, bs)
        cols = start + jax.lax.broadcasted_iota(
            jnp.int32, (H, block_size), 1
        )
        mask = cols < length
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[:, 0]                           # (H,)
        l_prev = l_ref[:, 0]
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        shift = jnp.where(m_new <= NEG_INF, 0.0, m_new)
        p = jnp.exp(s - shift[:, None])
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(jnp.where(m_prev <= NEG_INF, NEG_INF, m_prev - shift))
        l_ref[:] = jnp.broadcast_to(
            (l_prev * alpha + jnp.sum(p, axis=1))[:, None], l_ref.shape
        )
        # dequant v: scale varies along the contracted row axis — fold it
        # into the probabilities; same per-head 2D dots, same stored layout
        pv_heads = []
        for kh in range(kv_heads):
            v_h = v_ref[0][:, kh * head_dim:(kh + 1) * head_dim]  # (bs, D)
            p_h = p[kh * G:(kh + 1) * G] * vs[:, kh][None, :]     # (G, bs)
            pv_heads.append(jax.lax.dot_general(
                p_h.astype(q.dtype), v_h.astype(q.dtype),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ))                                         # (G, D)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jnp.concatenate(
            pv_heads, axis=0
        )
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)

    @pl.when(ji == num_j - 1)
    def _finalize():
        acc_out[0] = acc_ref[:]
        m_out[0] = m_ref[:]
        l_out[0] = l_ref[:]


def paged_attention_partial(
    q: jax.Array,             # (B, H, D)
    k_pool,                   # (nb, bs, Kh*D) bf16, or int8 {"q","s"} pool
    v_pool,
    block_tables: jax.Array,  # (B, max_blocks) int32
    lengths: jax.Array,       # (B,) int32 — cache rows to attend per slot
    *,
    num_read_blocks: int,     # static table columns to sweep (window bucket)
    kv_heads: int,
    head_dim: int,
    scale: float | None = None,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Partial (unnormalised) paged attention over the cache segment.

    Returns ``(acc (B,H,D) f32, m (B,H) f32, l (B,H) f32)`` for the caller
    to merge with other segments via :func:`merge_partial_attention`.

    int8 pools (``{"q": int8, "s": f32}`` dicts) read through the in-kernel
    fused-dequant twin — no densified bf16 window copy, which on the XLA
    gather path costs more HBM traffic than the weights themselves at
    serving batch sizes (r5 chip attribution).
    """
    if isinstance(k_pool, dict):
        return _paged_attention_partial_q8(
            q, k_pool, v_pool, block_tables, lengths,
            num_read_blocks=num_read_blocks, kv_heads=kv_heads,
            head_dim=head_dim, scale=scale, interpret=interpret,
        )
    B, H, D = q.shape
    nb, bs, KhD = k_pool.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    kernel = functools.partial(
        _paged_kernel,
        scale=scale,
        block_size=bs,
        kv_heads=kv_heads,
        head_dim=head_dim,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, num_read_blocks),
        in_specs=[
            pl.BlockSpec(
                (1, H, D), lambda b, j, tables, lengths: (b, 0, 0)
            ),
            pl.BlockSpec(
                (1, bs, KhD),
                lambda b, j, tables, lengths: (tables[b, j], 0, 0),
            ),
            pl.BlockSpec(
                (1, bs, KhD),
                lambda b, j, tables, lengths: (tables[b, j], 0, 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, H, D), lambda b, j, tables, lengths: (b, 0, 0)),
            pl.BlockSpec((1, H, 128), lambda b, j, tables, lengths: (b, 0, 0)),
            pl.BlockSpec((1, H, 128), lambda b, j, tables, lengths: (b, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, H, 128), jnp.float32),
        ],
        compiler_params=_compiler_params()(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(block_tables, lengths, q, k_pool, v_pool)
    return acc, m[:, :, 0], l[:, :, 0]


def _paged_attention_partial_q8(
    q: jax.Array,
    k_pool: dict,
    v_pool: dict,
    block_tables: jax.Array,
    lengths: jax.Array,
    *,
    num_read_blocks: int,
    kv_heads: int,
    head_dim: int,
    scale: float | None = None,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    B, H, D = q.shape
    nb, bs, KhD = k_pool["q"].shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    kernel = functools.partial(
        _paged_kernel_q8,
        scale=scale,
        block_size=bs,
        kv_heads=kv_heads,
        head_dim=head_dim,
    )
    block = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda b, j, tables, lengths: (tables[b, j], 0, 0)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, num_read_blocks),
        in_specs=[
            pl.BlockSpec(
                (1, H, D), lambda b, j, tables, lengths: (b, 0, 0)
            ),
            block((1, bs, KhD)),          # k int8
            block((1, bs, kv_heads)),     # k scales
            block((1, bs, KhD)),          # v int8
            block((1, bs, kv_heads)),     # v scales
        ],
        out_specs=[
            pl.BlockSpec((1, H, D), lambda b, j, tables, lengths: (b, 0, 0)),
            pl.BlockSpec((1, H, 128), lambda b, j, tables, lengths: (b, 0, 0)),
            pl.BlockSpec((1, H, 128), lambda b, j, tables, lengths: (b, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, H, 128), jnp.float32),
        ],
        compiler_params=_compiler_params()(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(block_tables, lengths, q, k_pool["q"], k_pool["s"],
      v_pool["q"], v_pool["s"])
    return acc, m[:, :, 0], l[:, :, 0]


def _paged_mq_kernel(
    tables_ref,   # SMEM (B, max_blocks) int32
    starts_ref,   # SMEM (B,) int32 — history rows per slot
    q_ref,        # (1, tb, H, D)
    k_ref,        # (1, bs, KhD)
    v_ref,        # (1, bs, KhD)
    acc_out,      # (1, tb*H, D) f32
    m_out,        # (1, tb*H, 8) f32 — narrow HBM output, lane 0 is read
    l_out,        # (1, tb*H, 8) f32
    m_ref,        # VMEM (tb*H, 128) f32
    l_ref,        # VMEM (tb*H, 128) f32
    acc_ref,      # VMEM (tb*H, D) f32
    *,
    scale: float,
    block_size: int,
    kv_heads: int,
    head_dim: int,
    t_block: int,
):
    b = pl.program_id(0)
    ji = pl.program_id(2)
    num_j = pl.num_programs(2)
    length = starts_ref[b]
    start = ji * block_size

    @pl.when(ji == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(start < length)
    def _accumulate():
        T = t_block
        D = head_dim
        H = acc_ref.shape[0] // T
        G = H // kv_heads
        q = q_ref[0]                                     # (T, H, D)
        k = k_ref[0].reshape(block_size, kv_heads, D)
        v = v_ref[0].reshape(block_size, kv_heads, D)
        # rows per kv head: T query positions × G grouped heads — every
        # history key is visible to every suffix query (rows < start), so
        # unlike causal attention the mask is uniform across the T axis
        qg = (
            q.reshape(T, kv_heads, G, D)
            .transpose(1, 0, 2, 3)
            .reshape(kv_heads, T * G, D)
        )
        kb = k.transpose(1, 0, 2)                        # (Kh, bs, D)
        s = jax.lax.dot_general(
            qg, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                                        # (Kh, T*G, bs)
        cols = start + jax.lax.broadcasted_iota(
            jnp.int32, (kv_heads, T * G, block_size), 2
        )
        mask = cols < length
        s = jnp.where(mask, s, NEG_INF)
        # working layout (T*H,) = (Kh, T, G) flattened to match acc rows
        TH = T * kv_heads * G
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_cur = jnp.max(s, axis=2).reshape(TH)
        m_new = jnp.maximum(m_prev, m_cur)
        shift = jnp.where(m_new <= NEG_INF, 0.0, m_new)
        p = jnp.exp(s - shift.reshape(kv_heads, T * G)[..., None])
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(jnp.where(m_prev <= NEG_INF, NEG_INF, m_prev - shift))
        l_ref[:] = jnp.broadcast_to(
            (l_prev * alpha + jnp.sum(p, axis=2).reshape(TH))[:, None],
            l_ref.shape,
        )
        vb = v.transpose(1, 0, 2)                        # (Kh, bs, D)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), vb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                # (Kh, T*G, D)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + pv.reshape(TH, D)
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)

    @pl.when(ji == num_j - 1)
    def _finalize():
        acc_out[0] = acc_ref[:]
        m_out[0] = m_ref[:, :8]
        l_out[0] = l_ref[:, :8]


def paged_attention_multiquery_partial(
    q: jax.Array,             # (B, T, H, D) — T suffix queries per slot
    k_pool: jax.Array,        # (nb, bs, Kh*D)
    v_pool: jax.Array,
    block_tables: jax.Array,  # (B, max_blocks) int32
    starts: jax.Array,        # (B,) int32 — history rows per slot
    *,
    num_read_blocks: int,
    kv_heads: int,
    head_dim: int,
    t_block: int = 16,
    scale: float | None = None,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Multi-query twin of :func:`paged_attention_partial`: T suffix
    queries per slot attend the slot's paged HISTORY (rows ``< starts``) —
    the continuation-prefill / speculative-verify hot read. History is
    mask-uniform across the T axis (causality among the suffix itself is
    the caller's separate XLA segment), so the kernel is the single-query
    sweep with a query-block grid axis and (T·G)-row MXU tiles instead of
    G-row ones.

    Returns ``(acc (B,T,H,D) f32, m (B,T,H) f32, l (B,T,H) f32)``.
    ``T`` must be a multiple of ``t_block``.
    """
    B, T, H, D = q.shape
    nb, bs, KhD = k_pool.shape
    if T % t_block:
        raise ValueError(f"T={T} must be a multiple of t_block={t_block}")
    nt = T // t_block
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    THb = t_block * H
    kernel = functools.partial(
        _paged_mq_kernel,
        scale=scale,
        block_size=bs,
        kv_heads=kv_heads,
        head_dim=head_dim,
        t_block=t_block,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, nt, num_read_blocks),
        in_specs=[
            pl.BlockSpec(
                (1, t_block, H, D),
                lambda b, t, j, tables, starts: (b, t, 0, 0),
            ),
            pl.BlockSpec(
                (1, bs, KhD),
                lambda b, t, j, tables, starts: (tables[b, j], 0, 0),
            ),
            pl.BlockSpec(
                (1, bs, KhD),
                lambda b, t, j, tables, starts: (tables[b, j], 0, 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, THb, D), lambda b, t, j, tables, starts: (b, t, 0)
            ),
            # m/l outputs are narrow (callers read one lane): the scratch
            # keeps the 128-lane compute layout, but materializing
            # (B, T·H, 128) f32 in HBM would be a 16× transient that now
            # scales with the suffix length
            pl.BlockSpec(
                (1, THb, 8), lambda b, t, j, tables, starts: (b, t, 0)
            ),
            pl.BlockSpec(
                (1, THb, 8), lambda b, t, j, tables, starts: (b, t, 0)
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((THb, 128), jnp.float32),
            pltpu.VMEM((THb, 128), jnp.float32),
            pltpu.VMEM((THb, D), jnp.float32),
        ],
    )
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, nt * THb, D), jnp.float32),
            jax.ShapeDtypeStruct((B, nt * THb, 8), jnp.float32),
            jax.ShapeDtypeStruct((B, nt * THb, 8), jnp.float32),
        ],
        compiler_params=_compiler_params()(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(block_tables, starts, q, k_pool, v_pool)
    # kernel rows are (Kh, t, G)-major per t-block → back to (B, T, H)
    G = H // kv_heads

    def unflatten(x, *trail):
        x = x.reshape(B, nt, kv_heads, t_block, G, *trail)
        x = x.transpose(0, 1, 3, 2, 4, *range(5, 5 + len(trail)))
        return x.reshape(B, T, H, *trail)

    acc = unflatten(acc, D)
    m = unflatten(m[:, :, 0])
    l = unflatten(l[:, :, 0])
    return acc, m, l


def shard_mapped_paged_read(
    fn,                       # per-shard partial fn(..., kv_heads=) → 3-tuple
    mesh,
    *,
    kv_heads: int,
    batch: int,
    q_spec_tail: tuple,       # q PartitionSpec entries AFTER the batch axis
    out_spec_tails: tuple,    # per-output spec entries after the batch axis
):
    """Shared mesh wrapper for the paged read kernels (decode single-query
    and continuation multi-query): slots on ``dp``, heads on ``tp`` (the
    pool's fused Kh·D axis splits on head boundaries), degrading an axis to
    replicated when the batch doesn't divide ``dp`` or the KV heads don't
    divide ``tp``. One copy so the two call sites can't drift."""
    from functools import partial as _partial

    from jax.sharding import PartitionSpec as P

    from langstream_tpu.jax_compat import shard_map

    axes = mesh.axis_names
    dp = (
        "dp"
        if "dp" in axes and mesh.shape["dp"] > 1 and batch % mesh.shape["dp"] == 0
        else None
    )
    tp = (
        "tp"
        if "tp" in axes
        and mesh.shape["tp"] > 1
        and kv_heads % mesh.shape["tp"] == 0
        else None
    )
    tp_size = mesh.shape["tp"] if tp else 1

    def sub(entry):
        return {"dp": dp, "tp": tp}.get(entry, entry) if entry else None

    q_spec = P(dp, *(sub(e) for e in q_spec_tail))
    return shard_map(
        _partial(fn, kv_heads=kv_heads // tp_size),
        mesh=mesh,
        in_specs=(
            q_spec,
            P(None, None, tp),  # k pool (nb, bs, Kh·D)
            P(None, None, tp),  # v pool
            P(dp, None),        # block tables (B, max_blocks)
            P(dp),              # lengths/starts (B,)
        ),
        out_specs=tuple(
            P(dp, *(sub(e) for e in tail)) for tail in out_spec_tails
        ),
        check_vma=False,
    )


def merge_partial_attention(
    parts: list[tuple[jax.Array, jax.Array, jax.Array]],
) -> jax.Array:
    """Combine per-segment ``(acc, m, l)`` partials into normalised attention
    output: the associative online-softmax merge."""
    acc, m, l = parts[0]
    for acc2, m2, l2 in parts[1:]:
        m_new = jnp.maximum(m, m2)
        shift = jnp.where(m_new <= NEG_INF, 0.0, m_new)
        a1 = jnp.exp(jnp.where(m <= NEG_INF, NEG_INF, m - shift))
        a2 = jnp.exp(jnp.where(m2 <= NEG_INF, NEG_INF, m2 - shift))
        acc = acc * a1[..., None] + acc2 * a2[..., None]
        l = l * a1 + l2 * a2
        m = m_new
    inv = jnp.where(l > 0.0, 1.0 / jnp.maximum(l, 1e-30), 0.0)
    return acc * inv[..., None]
