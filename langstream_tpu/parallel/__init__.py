"""Parallelism: meshes, sharding rules, sequence parallelism.

The reference's only parallelism is pod replication + broker partitions
(SURVEY.md §2.2); device-level parallelism is the capability gap this package
fills. Axes:

- ``dp`` — data parallel: request/batch fan-out (the device-level analogue of
  the reference's partition fan-out).
- ``tp`` — tensor parallel: Megatron-style sharded matmuls over ICI.
- ``sp`` — sequence parallel: ring attention for long contexts.

Everything is expressed as ``jax.sharding.NamedSharding`` over a ``Mesh`` —
XLA inserts the collectives (psum/all-gather/reduce-scatter) and schedules
them on ICI.
"""

from langstream_tpu.parallel.mesh import make_mesh, mesh_axes, local_mesh

__all__ = ["make_mesh", "mesh_axes", "local_mesh"]
