"""Mesh construction helpers."""

from __future__ import annotations

import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def mesh_axes(mesh: Mesh | None) -> tuple[str, ...]:
    return tuple(mesh.axis_names) if mesh is not None else ()


def make_mesh(
    axis_sizes: dict[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a mesh from axis sizes, e.g. ``{"dp": 2, "tp": 4}``.

    A size of ``-1`` on exactly one axis means "all remaining devices".
    Axis order follows dict order; put the fastest-communicating axis last
    (``tp`` innermost) so tensor-parallel collectives ride neighbouring ICI
    links.
    """
    devices = list(devices if devices is not None else jax.devices())
    axis_sizes = dict(axis_sizes or {"tp": len(devices)})
    wildcard = [k for k, v in axis_sizes.items() if v == -1]
    known = math.prod(v for v in axis_sizes.values() if v != -1)
    if wildcard:
        if len(wildcard) > 1:
            raise ValueError("only one axis may be -1")
        axis_sizes[wildcard[0]] = len(devices) // known
    total = math.prod(axis_sizes.values())
    if total > len(devices):
        raise ValueError(
            f"mesh {axis_sizes} needs {total} devices, have {len(devices)}"
        )
    grid = np.array(devices[:total]).reshape(tuple(axis_sizes.values()))
    return Mesh(grid, tuple(axis_sizes))


def local_mesh(tp: int | None = None, dp: int = 1, sp: int = 1) -> Mesh:
    """Convenience mesh over the local devices: ``(dp, sp, tp)``."""
    n = len(jax.devices())
    if tp is None:
        tp = n // (dp * sp)
    return make_mesh({"dp": dp, "sp": sp, "tp": tp})


def put_global(x, sharding):
    """``jax.device_put`` that also works in multi-controller runs: every
    process holds the full host value (identical by construction — same
    PRNG/checkpoint on every host) and contributes its addressable shards
    via ``make_array_from_callback``. Single-process: plain device_put."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )
