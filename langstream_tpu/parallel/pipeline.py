"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` mesh axis.

TPU-first design (nothing like this exists in the reference — SURVEY.md §2.2
documents pipeline parallelism *across agents via topics*; this module is the
in-model counterpart over ICI):

- The stacked layer tensors ``(L, ...)`` shard their layer axis over ``pp``:
  each device (stage) owns ``L/pp`` contiguous layers. No weight gathers —
  weights never move, activations do.
- A GPipe schedule runs inside ``jax.shard_map`` *manual over pp only*
  (``axis_names={"pp"}``): at tick ``t`` stage ``s`` processes microbatch
  ``t-s``; activations hop stage→stage with a single ``ppermute`` per tick
  over ICI. dp/tp/ep stay automatic, so Megatron TP and MoE expert
  parallelism compose inside a stage.
- Bubble fraction is the usual ``(pp-1)/(M+pp-1)`` — callers pick the
  microbatch count ``M`` accordingly.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from langstream_tpu.jax_compat import SHARD_MAP_PARTIAL_AUTO, shard_map

from langstream_tpu.models.llama import (
    LlamaConfig,
    _rms_norm,
    _swiglu,
    attention_block,
)
from langstream_tpu.models.llama import _rope as rope_tables


def gpipe(
    stage_fn: Callable[[jax.Array], tuple[jax.Array, jax.Array]],
    x_microbatches: jax.Array,  # (M, mb, S, H) — replicated over pp
    axis: str = "pp",
) -> tuple[jax.Array, jax.Array]:
    """Run the GPipe schedule; call INSIDE shard_map manual over ``axis``.

    ``stage_fn`` applies this stage's layers to one microbatch and returns
    ``(activations, aux_scalar)`` (aux = e.g. MoE load-balancing loss for
    the stage's layers; 0 when unused). Returns the fully-processed
    microbatches broadcast to every stage, plus the aux total summed over
    stages × microbatches.
    """
    pp = jax.lax.psum(1, axis)
    s = jax.lax.axis_index(axis)
    M = x_microbatches.shape[0]
    T = M + pp - 1  # total ticks (the (pp-1)/(M+pp-1) bubble)

    buf0 = jnp.zeros_like(x_microbatches[0])
    out0 = jnp.zeros_like(x_microbatches)
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def tick(carry, t):
        buf, out, aux_acc = carry
        # stage 0 feeds microbatch t; later stages consume the previous
        # tick's ppermute delivery (stage s sees microbatch t-s)
        feed = jax.lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, M - 1), 0, keepdims=False
        )
        inp = jnp.where(s == 0, feed, buf)
        y, aux = stage_fn(inp)
        # stage s holds a real microbatch only for ticks with 0 ≤ t-s < M
        valid = (t - s >= 0) & (t - s < M)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        # the last stage retires microbatch t-(pp-1)
        out_idx = t - (pp - 1)
        retired = jax.lax.dynamic_update_index_in_dim(
            out, y, jnp.clip(out_idx, 0, M - 1), 0
        )
        out = jnp.where((s == pp - 1) & (out_idx >= 0), retired, out)
        buf = jax.lax.ppermute(y, axis, perm)
        return (buf, out, aux_acc), None

    # scan (not fori_loop): the schedule must be reverse-differentiable so a
    # training step can backprop through the pipeline
    # the aux accumulator is rank-1, never a scalar: jax 0.4.x shard_map
    # partial-eval mis-names scalar residuals in the backward pass
    # (_SpecError from _check_names) — a (1,) carry sidesteps it
    (_, out, aux_acc), _ = jax.lax.scan(
        tick, (buf0, out0, jnp.zeros((1,), jnp.float32)), jnp.arange(T)
    )
    # results live on the last stage; psum broadcasts them (other stages
    # contribute zeros) so the head/loss runs identically everywhere.
    # the psum runs in f32: XLA's bf16 all-reduce promotion pass crashes on
    # CPU (and on TPU f32 accumulation is what we'd want anyway)
    dtype = out.dtype
    out = jnp.where(s == pp - 1, out, jnp.zeros_like(out)).astype(jnp.float32)
    out = jax.lax.psum(out, axis).astype(dtype)
    return out, jax.lax.psum(aux_acc, axis)


def pp_layer_specs(layer_specs: dict) -> dict:
    """Prepend ``pp`` on the stacked layer axis of each per-layer spec
    (e.g. ``P(None, None, 'tp')`` → ``P('pp', None, 'tp')``)."""
    return jax.tree.map(
        lambda spec: P("pp", *spec[1:]),
        layer_specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def _causal_attention(config):
    from langstream_tpu.parallel.ring import dense_attention

    return partial(
        dense_attention, causal=True, scale=1.0 / math.sqrt(config.head_dim)
    )


def _llama_layer(config: LlamaConfig, x: jax.Array, lp: dict, cos, sin):
    x = attention_block(config, x, lp, cos, sin, _causal_attention(config))
    h2 = _rms_norm(x, lp["mlp_norm"], config.norm_eps)
    return x + _swiglu(h2, lp["w_gate"], lp["w_up"], lp["w_down"])


def llama_forward_pp(
    config: LlamaConfig,
    params: dict,
    tokens: jax.Array,  # (B, S), B divisible by num_microbatches
    mesh: Mesh,
    num_microbatches: int = 4,
) -> jax.Array:
    """Pipeline-parallel all-position logits. Embed/head run outside the
    pipelined region (replicated or tp-sharded by their own specs); the layer
    stack runs as pp stages."""
    c = config
    B, S = tokens.shape
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    x = jnp.take(params["embed"], tokens, axis=0)
    # f32 across the shard_map boundary: the replicated input's cotangent is
    # psum'd over pp, and XLA-CPU's bf16 all-reduce promotion pass crashes
    x_mb = x.reshape(M, B // M, S, c.hidden).astype(jnp.float32)

    def stage(local_layers: dict, xm: jax.Array):
        xm = xm.astype(c.dtype)
        b = xm.shape[0]
        positions = jnp.arange(S)[None, :].repeat(b, axis=0)
        cos, sin = rope_tables(positions, c.head_dim, c.rope_theta)

        def body(x, lp):
            return _llama_layer(c, x, lp, cos, sin), None

        out, _ = jax.lax.scan(body, xm, local_layers)
        return out.astype(jnp.float32), jnp.float32(0.0)

    run = shard_map(
        lambda layers, xm: gpipe(partial(stage, layers), xm)[0],
        mesh=mesh,
        in_specs=(
            jax.tree.map(
                lambda _: P("pp"), params["layers"],
            ),
            P(),
        ),
        out_specs=P(),
        axis_names={"pp"},
        check_vma=False,
    )
    x = run(params["layers"], x_mb).reshape(B, S, c.hidden)
    x = _rms_norm(x, params["final_norm"], c.norm_eps)
    return jnp.einsum("bsh,hv->bsv", x, params["lm_head"]).astype(jnp.float32)


def moe_forward_pp(
    config,  # MoEConfig
    params: dict,
    tokens: jax.Array,
    mesh: Mesh,
    num_microbatches: int = 4,
) -> tuple[jax.Array, jax.Array]:
    """Pipeline-parallel MoE forward: pp stages over layers, expert
    parallelism (ep) + TP automatic *inside* each stage. Returns (logits,
    aux load-balancing loss)."""
    from langstream_tpu.models.moe import moe_ffn
    from jax.sharding import NamedSharding

    c = config
    B, S = tokens.shape
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    capacity = c.capacity((B // M) * S)
    axes = mesh.axis_names
    # in-stage ep constraints need partial-manual shard_map (pp manual,
    # ep/tp automatic); old jax runs the stage fully manual instead, where
    # a mesh-axis constraint is illegal — experts are simply replicated
    ep = "ep" if "ep" in axes and SHARD_MAP_PARTIAL_AUTO else None
    e_spec = NamedSharding(mesh, P(ep, None, None))

    x = jnp.take(params["embed"], tokens, axis=0)
    # f32 boundary (see llama_forward_pp): bf16 pp-psum of the replicated
    # input's cotangent crashes XLA-CPU's promotion pass
    x_mb = x.reshape(M, B // M, S, c.hidden).astype(jnp.float32)

    def stage_fn(local_layers: dict, xm: jax.Array):
        xm = xm.astype(c.dtype)
        b = xm.shape[0]
        positions = jnp.arange(S)[None, :].repeat(b, axis=0)
        cos, sin = rope_tables(positions, c.head_dim, c.rope_theta)

        def body(carry, lp):
            x, aux_acc = carry
            x = attention_block(c, x, lp, cos, sin, _causal_attention(c))
            h2 = _rms_norm(x, lp["mlp_norm"], c.norm_eps)
            ffn, aux = moe_ffn(
                h2, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
                capacity,
                ep_constrain=(
                    (lambda t: jax.lax.with_sharding_constraint(t, e_spec))
                    if ep
                    else None
                ),
            )
            # the aux accumulator is shape (1,), not a scalar: jax 0.4.x
            # shard_map partial-eval mis-names scalar residuals in the
            # backward pass (_SpecError) — a rank-1 carry sidesteps it
            return (x + ffn, aux_acc + aux.reshape(1)), None

        (out, aux_total), _ = jax.lax.scan(
            body, (xm, jnp.zeros((1,), jnp.float32)), local_layers
        )
        return out.astype(jnp.float32), aux_total

    run = shard_map(
        lambda layers, xm: gpipe(partial(stage_fn, layers), xm),
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P("pp"), params["layers"]),
            P(),
        ),
        out_specs=(P(), P()),
        axis_names={"pp"},
        check_vma=False,
    )
    x, aux_total = run(params["layers"], x_mb)
    x = x.reshape(B, S, c.hidden)
    x = _rms_norm(x, params["final_norm"], c.norm_eps)
    logits = jnp.einsum("bsh,hv->bsv", x, params["lm_head"]).astype(jnp.float32)
    return logits, aux_total.reshape(()) / M
