"""Ring attention + Ulysses all-to-all sequence/context parallelism.

Long-context support the TPU-first way: the sequence axis is sharded over a
mesh axis (``sp``) so each device holds ``S/n`` tokens, and attention runs as
a collective over ICI:

- **Ring attention** (:func:`ring_attention`): K/V shards rotate around the
  ``sp`` ring via ``jax.lax.ppermute`` while each device keeps its Q shard;
  softmax is accumulated online (running max / running sum, flash-attention
  style) so the full ``S x S`` score matrix never materialises. Per step the
  device overlaps one block of compute with one neighbour-to-neighbour ICI
  transfer — the canonical TPU ring schedule.
- **Ulysses** (:func:`ulysses_attention`): two ``all_to_all``s re-shard
  sequence→heads, run dense local attention, and re-shard back. Cheaper
  collectives for moderate context when heads ≥ ring size.

Both support GQA (separate Q-head and KV-head counts) and causal masking
with *global* positions (each device knows its block offset from
``lax.axis_index``).

Parity note: the reference has **no** long-context subsystem (SURVEY.md
§5.7 — context limits were the SaaS models'); this module fills that
capability gap as a first-class component rather than porting anything.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from langstream_tpu.jax_compat import shard_map


def _axis_or_none(mesh: Mesh, name: str | None) -> str | None:
    if name is None or mesh is None:
        return None
    return name if name in mesh.axis_names else None


# ---------------------------------------------------------------------------
# ring attention
# ---------------------------------------------------------------------------


def _ring_attention_local(
    q: jax.Array,  # (B, Sq, H, D) local Q shard
    k: jax.Array,  # (B, Sk, Kh, D) local K shard (rotates)
    v: jax.Array,  # (B, Sk, Kh, D)
    *,
    axis_name: str,
    causal: bool,
    scale: float,
) -> jax.Array:
    """Per-device body run under ``shard_map``: online-softmax attention over
    all K/V blocks as they rotate around the ``axis_name`` ring."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Sq, H, D = q.shape
    Sk, Kh = k.shape[1], k.shape[2]
    G = H // Kh
    qg = (q.astype(jnp.float32) * scale).reshape(B, Sq, Kh, G, D)
    q_pos = idx * Sq + jnp.arange(Sq)
    neg = jnp.float32(jnp.finfo(jnp.float32).min)

    # accumulators in (B, Kh, G, Sq, ...) layout
    m0 = jnp.full((B, Kh, G, Sq), neg, dtype=jnp.float32)
    l0 = jnp.zeros((B, Kh, G, Sq), dtype=jnp.float32)
    o0 = jnp.zeros((B, Kh, G, Sq, D), dtype=jnp.float32)

    def accumulate(o, l, m, k_blk, v_blk, s):
        j = (idx - s) % n  # global block index currently held
        scores = jnp.einsum(
            "bqkgd,bskd->bkgqs", qg, k_blk.astype(jnp.float32)
        )  # (B, Kh, G, Sq, Sk)
        if causal:
            k_pos = j * Sk + jnp.arange(Sk)
            mask = q_pos[:, None] >= k_pos[None, :]  # (Sq, Sk)
            scores = jnp.where(mask[None, None, None], scores, neg)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # rows with no valid key yet keep m=neg; exp(neg-neg) would NaN, so
        # guard the shift. (The s=0 diagonal block always validates each row
        # in the causal case, so by the end m_new is finite everywhere.)
        shift = jnp.where(m_new <= neg, 0.0, m_new)
        p = jnp.exp(scores - shift[..., None])
        if causal:
            p = jnp.where(mask[None, None, None], p, 0.0)
        alpha = jnp.exp(jnp.where(m <= neg, neg, m - shift))
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bkgqs,bskd->bkgqd", p, v_blk.astype(jnp.float32)
        )
        return o, l, m_new

    def maybe_accumulate(o, l, m, k_blk, v_blk, s):
        if not causal:
            return accumulate(o, l, m, k_blk, v_blk, s)
        # skip blocks entirely in the future (fully masked): without this,
        # causal ring attention burns ~2x the needed FLOPs — the masked
        # einsum/exp/matmul would still execute and then be zeroed
        j = (idx - s) % n
        needed = j * Sk <= idx * Sq + Sq - 1
        return lax.cond(
            needed,
            lambda args: accumulate(*args, s),
            lambda args: args[:3],
            (o, l, m, k_blk, v_blk),
        )

    def step(carry, s):
        o, l, m, k_blk, v_blk = carry
        o, l, m = maybe_accumulate(o, l, m, k_blk, v_blk, s)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (o, l, m, k_blk, v_blk), None

    # n-1 rotated steps, then the final block without the (wasted) rotation
    (o, l, m, k, v), _ = lax.scan(step, (o0, l0, m0, k, v), jnp.arange(n - 1))
    o, l, _ = maybe_accumulate(o, l, m, k, v, n - 1)
    out = o / jnp.maximum(l, 1e-30)[..., None]  # (B, Kh, G, Sq, D)
    out = out.transpose(0, 3, 1, 2, 4).reshape(B, Sq, H, D)
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,  # (B, S, H, D) global
    k: jax.Array,  # (B, S, Kh, D)
    v: jax.Array,  # (B, S, Kh, D)
    mesh: Mesh,
    *,
    causal: bool = True,
    seq_axis: str = "sp",
    head_axis: str | None = "tp",
    batch_axis: str | None = "dp",
    scale: float | None = None,
) -> jax.Array:
    """Sequence-parallel attention: seq sharded over ``seq_axis``, heads over
    ``head_axis`` (if present in the mesh), batch over ``batch_axis``.

    Composable with tensor parallelism: with ``head_axis="tp"`` each device
    ring-attends over its own head shard (requires ``Kh % tp == 0``).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    ba = _axis_or_none(mesh, batch_axis)
    ha = _axis_or_none(mesh, head_axis)
    sa = _axis_or_none(mesh, seq_axis)
    if sa is None:
        raise ValueError(f"mesh {mesh.axis_names} has no sequence axis {seq_axis!r}")
    spec = P(ba, sa, ha, None)
    fn = shard_map(
        partial(_ring_attention_local, axis_name=sa, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all head/sequence re-sharding)
# ---------------------------------------------------------------------------


def dense_attention(q, k, v, *, causal: bool, scale: float, q_offset=0):
    """Dense GQA attention. q: (B, Sq, H, D); k/v: (B, Sk, Kh, D).

    Matmuls run in the input dtype (bf16 on the model path — full MXU rate)
    with f32 accumulation via ``preferred_element_type``; only the softmax
    itself is f32.
    """
    B, Sq, H, D = q.shape
    Sk, Kh = k.shape[1], k.shape[2]
    G = H // Kh
    qg = q.reshape(B, Sq, Kh, G, D)
    scores = (
        jnp.einsum(
            "bqkgd,bskd->bkgqs", qg, k, preferred_element_type=jnp.float32
        )
        * scale
    )
    if causal:
        mask = (q_offset + jnp.arange(Sq))[:, None] >= jnp.arange(Sk)[None, :]
        scores = jnp.where(
            mask[None, None, None], scores, jnp.finfo(jnp.float32).min
        )
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgqs,bskd->bkgqd",
        probs.astype(q.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Sq, H, D).astype(q.dtype)


# Backwards-compatible private alias (pre-public-API name).
_dense_attention = dense_attention


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool, scale: float):
    """Under shard_map: re-shard seq→heads, dense-attend, re-shard back."""
    n = lax.psum(1, axis_name)
    Kh = k.shape[2]
    if Kh < n:
        # fewer KV heads than ring size: expand GQA groups so the head
        # all-to-all divides evenly (costs replicated K/V bandwidth, like
        # every Ulysses implementation with GQA)
        reps = n // Kh
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    # (B, S/n, H, D) -> (B, S, H/n, D)
    q = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = dense_attention(q, k, v, causal=causal, scale=scale)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    seq_axis: str = "sp",
    batch_axis: str | None = "dp",
    scale: float | None = None,
) -> jax.Array:
    """All-to-all sequence parallelism (Ulysses): seq-sharded in/out, dense
    attention over head-sharded tensors in the middle. Requires
    ``H % sp == 0``; KV heads are group-expanded when ``Kh < sp``."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    ba = _axis_or_none(mesh, batch_axis)
    sa = _axis_or_none(mesh, seq_axis)
    if sa is None:
        raise ValueError(f"mesh {mesh.axis_names} has no sequence axis {seq_axis!r}")
    spec = P(ba, sa, None, None)
    fn = shard_map(
        partial(_ulysses_local, axis_name=sa, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
