"""L3a/L4: streaming runtimes + the agent runner.

Importing this package registers the built-in streaming runtimes with
:class:`~langstream_tpu.api.topics.TopicConnectionsRuntimeRegistry`:

- ``memory`` — the first-party in-process partitioned broker (the role the
  embedded Kafka plays in the reference's ``langstream docker run`` tester).
- ``kafka`` — the SDK-backed runtime when ``confluent_kafka`` is
  importable (dynamic consumer groups); otherwise the in-tree WIRE
  runtime (``runtime/kafka_wire.py`` speaks the protocol itself —
  record batches v2, produce/fetch/offsets — with static partition
  assignment; same contiguous-commit semantics either way).
- ``pulsar`` — gated on the ``pulsar`` client library
  (``runtime/pulsar_broker.py``; semantics unit-tested against a fake
  client).
"""

from langstream_tpu.api.topics import TopicConnectionsRuntimeRegistry
from langstream_tpu.runtime.memory_broker import MemoryTopicConnectionsRuntime

TopicConnectionsRuntimeRegistry.register("memory", MemoryTopicConnectionsRuntime)

# ``tpustream`` — the in-tree native C++ broker (langstream_tpu/native/
# tsbroker.cc) speaking its own wire protocol; the framework's first-party
# answer to the reference's external Kafka cluster.
from langstream_tpu.runtime.tsb import TsbTopicConnectionsRuntime  # noqa: E402,F401

# ``type: kafka`` always registers: the selector picks the backend per the
# ``client`` config key (wire|sdk|auto — auto prefers confluent_kafka when
# importable, else the in-tree wire protocol).
from langstream_tpu.runtime.kafka_wire_runtime import (  # noqa: E402
    KafkaTopicConnectionsRuntimeSelector,
)

TopicConnectionsRuntimeRegistry.register(
    "kafka", KafkaTopicConnectionsRuntimeSelector
)

try:  # pragma: no cover - pulsar client not in the image
    import pulsar  # noqa: F401

    from langstream_tpu.runtime.pulsar_broker import PulsarTopicConnectionsRuntime

    TopicConnectionsRuntimeRegistry.register("pulsar", PulsarTopicConnectionsRuntime)
except ImportError:
    pass

try:  # pragma: no cover - pravega binding not in the image
    import pravega_client  # noqa: F401

    from langstream_tpu.runtime.pravega_broker import PravegaTopicConnectionsRuntime

    TopicConnectionsRuntimeRegistry.register(
        "pravega", PravegaTopicConnectionsRuntime
    )
except ImportError:
    pass

from langstream_tpu.runtime.runner import AgentRunner  # noqa: E402
from langstream_tpu.runtime.local_runner import LocalApplicationRunner  # noqa: E402

__all__ = [
    "AgentRunner",
    "LocalApplicationRunner",
    "MemoryTopicConnectionsRuntime",
]
