"""L3a/L4: streaming runtimes + the agent runner.

Importing this package registers the built-in streaming runtimes with
:class:`~langstream_tpu.api.topics.TopicConnectionsRuntimeRegistry`:

- ``memory`` — the first-party in-process partitioned broker (the role the
  embedded Kafka plays in the reference's ``langstream docker run`` tester).
- ``kafka`` — only when a Kafka client library is importable (none is baked
  into this image; the implementation is gated, not stubbed).
- ``pulsar`` — likewise gated on the ``pulsar`` client library
  (``runtime/pulsar_broker.py``; semantics unit-tested against a fake
  client, same strategy as kafka).
"""

from langstream_tpu.api.topics import TopicConnectionsRuntimeRegistry
from langstream_tpu.runtime.memory_broker import MemoryTopicConnectionsRuntime

TopicConnectionsRuntimeRegistry.register("memory", MemoryTopicConnectionsRuntime)

# ``tpustream`` — the in-tree native C++ broker (langstream_tpu/native/
# tsbroker.cc) speaking its own wire protocol; the framework's first-party
# answer to the reference's external Kafka cluster.
from langstream_tpu.runtime.tsb import TsbTopicConnectionsRuntime  # noqa: E402,F401

try:  # pragma: no cover - kafka client not in the image
    import confluent_kafka  # noqa: F401

    from langstream_tpu.runtime.kafka_broker import KafkaTopicConnectionsRuntime

    TopicConnectionsRuntimeRegistry.register("kafka", KafkaTopicConnectionsRuntime)
except ImportError:
    pass

try:  # pragma: no cover - pulsar client not in the image
    import pulsar  # noqa: F401

    from langstream_tpu.runtime.pulsar_broker import PulsarTopicConnectionsRuntime

    TopicConnectionsRuntimeRegistry.register("pulsar", PulsarTopicConnectionsRuntime)
except ImportError:
    pass

try:  # pragma: no cover - pravega binding not in the image
    import pravega_client  # noqa: F401

    from langstream_tpu.runtime.pravega_broker import PravegaTopicConnectionsRuntime

    TopicConnectionsRuntimeRegistry.register(
        "pravega", PravegaTopicConnectionsRuntime
    )
except ImportError:
    pass

from langstream_tpu.runtime.runner import AgentRunner  # noqa: E402
from langstream_tpu.runtime.local_runner import LocalApplicationRunner  # noqa: E402

__all__ = [
    "AgentRunner",
    "LocalApplicationRunner",
    "MemoryTopicConnectionsRuntime",
]
