"""Composite (fused) agent execution.

Parity: ``CompositeAgentProcessor``
(``langstream-runtime-impl/.../agent/CompositeAgentProcessor.java:36,150``):
the planner fuses consecutive composable stages into one node; at runtime the
stages chain in-memory — each source record flows through every stage, fan-out
included, with per-source-record error attribution preserved.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any

from langstream_tpu.api.agent import (
    AgentContext,
    AgentProcessor,
    ComponentType,
    RecordSink,
    SourceRecordAndResult,
)
from langstream_tpu.api.record import Record
from langstream_tpu.core.asyncutil import spawn_retained

log = logging.getLogger(__name__)


class _CollectorSink:
    """RecordSink that resolves a future once all expected source records
    have reported a result."""

    def __init__(self, expected: int):
        self.expected = expected
        self.results: list[SourceRecordAndResult] = []
        self.future: asyncio.Future[list[SourceRecordAndResult]] = (
            asyncio.get_running_loop().create_future()
        )

    def emit(self, result: SourceRecordAndResult) -> None:
        self.results.append(result)
        if len(self.results) >= self.expected and not self.future.done():
            self.future.set_result(self.results)

    def emit_error(self, source_record: Record, error: Exception) -> None:
        self.emit(SourceRecordAndResult(source_record, [], error))


async def process_await(
    processor: AgentProcessor, records: list[Record]
) -> list[SourceRecordAndResult]:
    """Drive one processor call to completion and gather its emissions."""
    if not records:
        return []
    collector = _CollectorSink(len(records))
    processor.process(records, collector)
    return await collector.future


class CompositeAgentProcessor(AgentProcessor):
    """Chains N processors; emits final results attributed to the original
    source record. Any stage error fails the source record as a whole."""

    def __init__(self, processors: list[AgentProcessor]):
        self.processors = processors
        # strong refs to in-flight per-record chains: the event loop keeps
        # only a weak reference, so an unretained task can be collected
        # mid-chain and its error never reaches the sink (FLOW1003)
        self._chains: set[asyncio.Task] = set()

    async def init(self, configuration: dict[str, Any]) -> None:
        self.configuration = configuration

    async def setup(self, context: AgentContext) -> None:
        self.context = context
        for p in self.processors:
            await p.setup(context)

    async def start(self) -> None:
        for p in self.processors:
            await p.start()

    async def close(self) -> None:
        for p in self.processors:
            await p.close()

    def component_type(self) -> ComponentType:
        return ComponentType.PROCESSOR

    def agent_info(self) -> dict[str, Any]:
        return {
            "composite": [
                {"type": p.agent_type, "info": p.agent_info()} for p in self.processors
            ]
        }

    def process(self, records: list[Record], sink: RecordSink) -> None:
        for record in records:
            # the sink emit below is the real error report — the
            # spawn_retained log line is a DEBUG audit trail, not a
            # second ERROR for a failure the framework already handles
            task = spawn_retained(
                self._chain_one(record), self._chains, log,
                "composite chain task failed", level=logging.DEBUG,
            )

            def _done(t: "asyncio.Task", r: Record = record) -> None:
                if t.cancelled():
                    return  # loop shutdown: no result to attribute
                err = t.exception()
                if err is not None:
                    sink.emit(
                        SourceRecordAndResult(
                            r, [], err if isinstance(err, Exception) else Exception(str(err))
                        )
                    )
                else:
                    sink.emit(SourceRecordAndResult(r, t.result(), None))

            task.add_done_callback(_done)

    async def _chain_one(self, record: Record) -> list[Record]:
        from langstream_tpu.core.tracing import TRACE_HEADER, start_span

        parent = record.header(TRACE_HEADER)
        service = getattr(
            getattr(self, "context", None), "global_agent_id", ""
        ) or "composite"
        current: list[Record] = [record]
        for stage in self.processors:
            if not current:
                return []
            next_records: list[Record] = []
            span = start_span(
                f"stage.{stage.agent_id or stage.agent_type}",
                service=service,
                parent=parent,
                attributes={"stage-type": stage.agent_type},
            )
            try:
                results = await process_await(stage, current)
                for res in results:
                    if res.error is not None:
                        raise res.error
                    next_records.extend(res.results)
            except Exception as e:
                span.end(error=e)
                raise
            span.set_attribute("records-out", len(next_records))
            span.end()
            current = next_records
        return current
