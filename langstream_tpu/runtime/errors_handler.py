"""Record-level error policy: fail | skip | dead-letter, with retries.

Parity: ``StandardErrorsHandler`` + ``ErrorsSpec``
(``langstream-runtime-impl/.../agent/errors/StandardErrorsHandler.java``;
``langstream-api/.../model/ErrorsSpec.java:28-37``). Retrying a single record
is inherently out-of-order relative to the rest of the batch (documented so in
the reference, ``AgentRunner.java:884-895``); commit contiguity still holds
because offsets commit by prefix.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from enum import Enum

from langstream_tpu.api.application import ErrorsSpec
from langstream_tpu.api.record import Record

log = logging.getLogger(__name__)


class FailureAction(Enum):
    RETRY = "retry"
    SKIP = "skip"
    DEAD_LETTER = "dead-letter"
    FAIL = "fail"


@dataclass
class StandardErrorsHandler:
    spec: ErrorsSpec = field(default_factory=ErrorsSpec)
    _attempts: dict[int, int] = field(default_factory=dict)

    def handle(self, record: Record, error: Exception) -> FailureAction:
        rid = id(record)
        attempts = self._attempts.get(rid, 0) + 1
        self._attempts[rid] = attempts
        log.warning(
            "record failed (attempt %d/%d): %s", attempts, self.spec.retries + 1, error
        )
        if attempts <= self.spec.retries:
            return FailureAction.RETRY
        self._attempts.pop(rid, None)
        return self._final_action()

    def clear(self, record: Record) -> None:
        """Forget attempt state once a record reaches a terminal state —
        required because ``id()`` keys can be recycled by the allocator."""
        self._attempts.pop(id(record), None)

    def _final_action(self) -> FailureAction:
        if self.spec.on_failure == ErrorsSpec.SKIP:
            return FailureAction.SKIP
        if self.spec.on_failure == ErrorsSpec.DEAD_LETTER:
            return FailureAction.DEAD_LETTER
        return FailureAction.FAIL


def deadletter_record(record: Record, error: Exception) -> Record:
    """Annotate the failed record for the dead-letter topic (parity: the
    reference attaches error cause headers)."""
    return record.with_headers(
        {
            "langstream-error-message": str(error),
            "langstream-error-class": type(error).__name__,
        }
    )
