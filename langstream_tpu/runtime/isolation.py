"""Per-application dependency isolation — the NAR-classloader answer.

The reference packages each agent family as a NAR with an isolated
classloader (``NarFileHandler.java:44,123``), so one agent's dependencies
cannot clash with another's. The Python-native equivalent here is a
**venv-per-application** policy for sidecar agents:

- An application that ships a ``python/requirements.txt`` gets its own venv
  (created with ``--system-site-packages`` so jax & friends resolve from the
  base image) under ``<app>/.venv`` (or ``LS_VENV_ROOT``). Its pinned deps
  install into that venv only.
- Sidecar agents (the gRPC lane) for that application run on the venv's
  interpreter, so conflicting pins between two applications never meet in
  one process. In-process agents always see only the base environment —
  declaring requirements forces the sidecar lane, which is the policy:
  isolation happens at the process boundary, exactly where the reference
  puts its classloader boundary.
- Offline installs: a shipped ``python/wheels/`` directory is used as the
  pip ``--find-links`` source with ``--no-index`` (this image has no
  network egress; in-cluster deployments may allow an index via
  ``LS_PIP_ARGS``).

``ensure_app_interpreter`` is idempotent and cheap when the venv already
matches the requirements file (content hash marker).
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess
import sys
from pathlib import Path

log = logging.getLogger(__name__)


def requirements_file(app_dir: str | Path) -> Path | None:
    for candidate in ("python/requirements.txt", "requirements.txt"):
        path = Path(app_dir) / candidate
        if path.is_file():
            return path
    return None


def ensure_app_interpreter(app_dir: str | Path | None) -> str:
    """Return the interpreter path sidecars of this application must run on:
    the app venv's python when the app pins requirements, else the current
    interpreter. Creates/updates the venv as needed."""
    if not app_dir:
        return sys.executable
    reqs = requirements_file(app_dir)
    if reqs is None:
        return sys.executable
    venv_root = os.environ.get("LS_VENV_ROOT")
    if venv_root:
        # a shared root still gets one venv PER APPLICATION — keyed by the
        # app path — or two apps' conflicting pins would fight over one venv
        app_key = hashlib.sha256(
            str(Path(app_dir).resolve()).encode()
        ).hexdigest()[:16]
        venv_dir = Path(venv_root) / f"venv-{app_key}"
    else:
        venv_dir = Path(app_dir) / ".venv"
    python = venv_dir / "bin" / "python"
    marker = venv_dir / ".requirements.sha256"
    digest = hashlib.sha256(reqs.read_bytes()).hexdigest()
    if python.exists() and marker.exists() and marker.read_text() == digest:
        return str(python)
    log.info("provisioning app venv at %s (requirements changed)", venv_dir)
    if venv_dir.exists():
        # changed requirements rebuild from scratch: an in-place reinstall
        # would leave packages dropped from the pin list behind, making the
        # environment diverge from a fresh deploy of the same app
        import shutil

        shutil.rmtree(venv_dir)
    subprocess.run(
        [sys.executable, "-m", "venv", "--system-site-packages", str(venv_dir)],
        check=True,
    )
    # --system-site-packages exposes the BASE interpreter's site dirs, but
    # this runtime usually runs inside a venv itself (whose site dir the
    # child venv cannot see). A .pth makes the parent environment's packages
    # resolvable; path order keeps the app venv's own pins winning.
    import site

    parent_sites = [p for p in site.getsitepackages() if Path(p).is_dir()]
    for child_site in venv_dir.glob("lib/python*/site-packages"):
        (child_site / "_langstream_parent_env.pth").write_text(
            "\n".join(parent_sites) + "\n"
        )
    pip_args = [str(python), "-m", "pip", "install", "-r", str(reqs)]
    wheels = Path(app_dir) / "python" / "wheels"
    if wheels.is_dir():
        pip_args += ["--no-index", "--find-links", str(wheels)]
    extra = os.environ.get("LS_PIP_ARGS")
    if extra:
        pip_args += extra.split()
    result = subprocess.run(pip_args, capture_output=True, text=True)
    if result.returncode != 0:
        raise RuntimeError(
            f"app venv install failed for {reqs}:\n{result.stderr[-2000:]}"
        )
    marker.write_text(digest)
    return str(python)
