"""Kafka streaming runtime (gated on a client library).

Parity: ``langstream-kafka-runtime`` — consumer wrapper with out-of-order
acknowledgement and contiguous-prefix offset commits
(``KafkaConsumerWrapper.java:41,52,203``), producer wrapper with serializer
inference (``KafkaProducerWrapper.java``), position-addressed reader for the
gateway (``KafkaReaderWrapper.java``), dead-letter producer
(``KafkaTopicConnectionsRuntime.java:123``) and topic admin.

The broker-facing calls go through ``confluent_kafka`` (not baked into this
image — the runtime registers only when it is importable, see
``langstream_tpu/runtime/__init__.py``). All commit *semantics* live in
:class:`ContiguousOffsetTracker`, pure Python, unit-tested against a fake
client in ``tests/test_kafka_runtime.py``.

Design notes (TPU build): Kafka is one pluggable inter-agent transport over
DCN next to the in-tree brokers (``memory``, ``tpustream``); nothing below
the topic SPI leaks into the serving path, which moves tensors over ICI via
XLA collectives, never through the broker.
"""

from __future__ import annotations

import asyncio
import json
import logging
import uuid
from typing import Any, Callable

from langstream_tpu.api.record import Record, SimpleRecord, now_millis
from langstream_tpu.api.topics import (
    OFFSET_HEADER,
    TopicAdmin,
    TopicConsumer,
    TopicConnectionsRuntime,
    TopicOffset,
    TopicProducer,
    TopicReader,
)

logger = logging.getLogger(__name__)


def _kafka():
    import confluent_kafka

    return confluent_kafka


# ---------------------------------------------------------------------------
# Commit semantics (pure)
# ---------------------------------------------------------------------------


class _PartitionState:
    __slots__ = ("position", "acked", "delivered_max")

    def __init__(self, position: int) -> None:
        self.position = position  # next offset the broker should resume at
        self.acked: set[int] = set()
        self.delivered_max = position - 1

    def deliver(self, offset: int) -> None:
        if offset > self.delivered_max:
            self.delivered_max = offset

    def ack(self, offset: int) -> int | None:
        """Mark ``offset`` processed; return the new commit position if the
        contiguous prefix advanced, else None."""
        if offset < self.position:
            return None
        self.acked.add(offset)
        advanced = False
        while self.position in self.acked:
            self.acked.discard(self.position)
            self.position += 1
            advanced = True
        return self.position if advanced else None


class ContiguousOffsetTracker:
    """Out-of-order acks, contiguous commits — the at-least-once backbone.

    Mirrors the reference's per-partition ``TreeSet`` of uncommitted offsets:
    records may complete in any order (async sinks, retries), but the offset
    committed to the broker only ever advances over the longest contiguous
    prefix of acknowledged offsets, so a crash redelivers every unacked
    record (``KafkaConsumerWrapper.java:194-203``).
    """

    def __init__(self) -> None:
        self._parts: dict[tuple[str, int], _PartitionState] = {}

    def start_partition(self, topic: str, partition: int, position: int) -> None:
        self._parts[(topic, partition)] = _PartitionState(position)

    def drop_partition(self, topic: str, partition: int) -> None:
        self._parts.pop((topic, partition), None)

    def delivered(self, topic: str, partition: int, offset: int) -> None:
        state = self._parts.get((topic, partition))
        if state is None:
            state = _PartitionState(offset)
            self._parts[(topic, partition)] = state
        state.deliver(offset)

    def acknowledge(self, topic: str, partition: int, offset: int) -> int | None:
        """Returns the new commit position for the partition when the
        contiguous prefix advanced, else None."""
        state = self._parts.get((topic, partition))
        if state is None:
            return None
        return state.ack(offset)

    def pending(self, topic: str, partition: int) -> int:
        """Delivered-but-unacked count (gap width + tail)."""
        state = self._parts.get((topic, partition))
        if state is None:
            return 0
        return (state.delivered_max - state.position + 1) - len(state.acked)


# ---------------------------------------------------------------------------
# Serde inference (KafkaProducerWrapper parity)
# ---------------------------------------------------------------------------


# Wire headers carrying the inferred serializers, so structured datums
# (dict/list/numbers, incl. header values) round-trip through the
# byte-oriented broker the way the reference's schema-aware Kafka serdes do.
VALUE_KIND_HEADER = "__ls_vkind"
KEY_KIND_HEADER = "__ls_kkind"
HEADER_KINDS_HEADER = "__ls_hkinds"  # JSON map: header name -> kind
_KIND_HEADERS = (VALUE_KIND_HEADER, KEY_KIND_HEADER, HEADER_KINDS_HEADER)


def serialize_datum(value: Any) -> bytes | None:
    """Infer the wire encoding from the Python type, like the reference's
    producer picks a Kafka serializer from the record's class."""
    data, _ = serialize_datum_kind(value)
    return data


def serialize_datum_kind(value: Any) -> tuple[bytes | None, str | None]:
    if value is None:
        return None, None
    if isinstance(value, bytes):
        return value, None
    if isinstance(value, str):
        return value.encode("utf-8"), None
    if isinstance(value, (dict, list, bool, int, float)):
        return json.dumps(value).encode("utf-8"), "json"
    return str(value).encode("utf-8"), None


def deserialize_datum(raw: bytes | None, kind: Any = None) -> Any:
    if raw is None:
        return None
    if kind is not None:
        kind = kind.decode() if isinstance(kind, bytes) else kind
    if kind == "json":
        try:
            return json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError):
            pass
    try:
        return raw.decode("utf-8")
    except UnicodeDecodeError:
        return raw


def record_headers_to_kafka(record: Record) -> list[tuple[str, bytes]]:
    out: list[tuple[str, bytes]] = []
    kinds: dict[str, str] = {}
    for k, v in record.headers:
        if k == OFFSET_HEADER:
            continue  # transport-local, never re-published
        data, kind = serialize_datum_kind(v)
        if data is None:
            data, kind = b"", "null"
        if kind:
            kinds[k] = kind
        out.append((k, data))
    if kinds:
        out.append((HEADER_KINDS_HEADER, json.dumps(kinds).encode()))
    return out


def record_wire_payload(
    record: Record,
) -> tuple[bytes | None, bytes | None, list[tuple[str, bytes]]]:
    """(key, value, headers) in the on-wire form BOTH kafka lanes share —
    serializer inference plus the kind headers that make deserialization
    reversible. One implementation so the SDK and wire runtimes can never
    diverge on the format of the same ``type: kafka`` topic."""
    value, vkind = serialize_datum_kind(record.value)
    key, kkind = serialize_datum_kind(record.key)
    headers = record_headers_to_kafka(record)
    if vkind:
        headers.append((VALUE_KIND_HEADER, vkind.encode()))
    if kkind:
        headers.append((KEY_KIND_HEADER, kkind.encode()))
    return key, value, headers


def kafka_message_to_record(msg: Any) -> Record:
    raw_headers = list(msg.headers() or [])
    kinds = {k: v for k, v in raw_headers if k in _KIND_HEADERS}
    hkinds_raw = kinds.get(HEADER_KINDS_HEADER)
    hkinds: dict[str, str] = {}
    if hkinds_raw is not None:
        try:
            hkinds = json.loads(
                hkinds_raw.decode() if isinstance(hkinds_raw, bytes) else hkinds_raw
            )
        except (json.JSONDecodeError, UnicodeDecodeError):
            pass
    headers = tuple(
        (k, None if hkinds.get(k) == "null" else deserialize_datum(v, hkinds.get(k)))
        for k, v in raw_headers
        if k not in _KIND_HEADERS
    ) + ((OFFSET_HEADER, TopicOffset(msg.topic(), msg.partition(), msg.offset())),)
    ts = None
    try:
        ts_type, ts_value = msg.timestamp()
        if ts_value and ts_value > 0:
            ts = ts_value
    except Exception as e:
        logger.debug("message has no usable timestamp: %s", e)
    return SimpleRecord(
        value=deserialize_datum(msg.value(), kinds.get(VALUE_KIND_HEADER)),
        key=deserialize_datum(msg.key(), kinds.get(KEY_KIND_HEADER)),
        headers=headers,
        origin=msg.topic(),
        timestamp=ts if ts is not None else now_millis(),
    )


# ---------------------------------------------------------------------------
# Consumer / producer / reader / admin
# ---------------------------------------------------------------------------


class KafkaTopicConsumer(TopicConsumer):
    """Group consumer; blocking client calls run on the default executor.

    The runner's loop serializes read/commit, and rebalance callbacks fire
    inside ``poll`` on the same thread, so client access is single-threaded
    as the client requires.
    """

    def __init__(
        self,
        bootstrap: dict[str, Any],
        topic: str,
        group: str,
        poll_batch: int = 64,
        poll_timeout: float = 0.5,
        consumer_factory: Callable[[dict], Any] | None = None,
    ):
        self.topic = topic
        self.group = group
        self.poll_batch = poll_batch
        self.poll_timeout = poll_timeout
        self.tracker = ContiguousOffsetTracker()
        self._conf = {
            **bootstrap,
            "group.id": group,
            "enable.auto.commit": False,
            "auto.offset.reset": "earliest",
        }
        self._factory = consumer_factory
        self._consumer: Any = None
        self._total_out = 0

    def _build(self) -> Any:
        if self._factory is not None:
            return self._factory(self._conf)
        return _kafka().Consumer(self._conf)

    async def start(self) -> None:
        if self._consumer is not None:
            return
        self._consumer = self._build()
        self._consumer.subscribe(
            [self.topic], on_assign=self._on_assign, on_revoke=self._on_revoke
        )

    # Rebalance listeners (parity: KafkaConsumerWrapper.java:82-112) — a
    # newly-assigned partition resumes at its committed position, so any
    # delivered-but-uncommitted records are redelivered (at-least-once).
    def _on_assign(self, consumer: Any, partitions: list[Any]) -> None:
        for tp in partitions:
            if tp.offset >= 0:
                self.tracker.start_partition(tp.topic, tp.partition, tp.offset)
            # tp.offset is OFFSET_INVALID (-1001) in normal rebalances: the
            # broker resumes delivery at the group's committed position, so
            # the tracker adopts the first *delivered* offset as its start
            # (ContiguousOffsetTracker.delivered creates the partition state
            # lazily). Seeding 0 here would wedge commits forever on any
            # partition resumed past offset 0.
            logger.info(
                "partition assigned %s[%d] at %s", tp.topic, tp.partition, tp.offset
            )

    def _on_revoke(self, consumer: Any, partitions: list[Any]) -> None:
        for tp in partitions:
            pending = self.tracker.pending(tp.topic, tp.partition)
            if pending:
                logger.warning(
                    "partition %s[%d] revoked with %d in-flight records; "
                    "they will be redelivered to the next assignee",
                    tp.topic, tp.partition, pending,
                )
            self.tracker.drop_partition(tp.topic, tp.partition)

    async def close(self) -> None:
        if self._consumer is None:
            return
        consumer, self._consumer = self._consumer, None
        await asyncio.get_running_loop().run_in_executor(None, consumer.close)

    async def read(self) -> list[Record]:
        loop = asyncio.get_running_loop()
        msgs = await loop.run_in_executor(
            None, self._consumer.consume, self.poll_batch, self.poll_timeout
        )
        batch: list[Record] = []
        for msg in msgs or []:
            if msg.error():
                err = msg.error()
                if getattr(err, "retriable", lambda: False)():
                    logger.warning("retriable consumer error: %s", err)
                    continue
                if self._is_partition_eof(err):
                    continue
                raise RuntimeError(f"kafka consumer error: {err}")
            self.tracker.delivered(msg.topic(), msg.partition(), msg.offset())
            batch.append(kafka_message_to_record(msg))
        self._total_out += len(batch)
        return batch

    @staticmethod
    def _is_partition_eof(err: Any) -> bool:
        try:
            return err.code() == _kafka().KafkaError._PARTITION_EOF
        except Exception:
            return False

    async def commit(self, records: list[Record]) -> None:
        to_commit: dict[tuple[str, int], int] = {}
        for record in records:
            offset: TopicOffset | None = record.header(OFFSET_HEADER)
            if offset is None:
                continue
            position = self.tracker.acknowledge(
                offset.topic, offset.partition, offset.offset
            )
            if position is not None:
                to_commit[(offset.topic, offset.partition)] = position
        if not to_commit:
            return
        kafka = _kafka()
        tps = [
            kafka.TopicPartition(topic, partition, position)
            for (topic, partition), position in to_commit.items()
        ]
        loop = asyncio.get_running_loop()
        # captured on the loop thread: close() nulls the field, and the
        # executor closure must not re-read it mid-flight (RACE801)
        consumer = self._consumer
        await loop.run_in_executor(
            None, lambda: consumer.commit(offsets=tps, asynchronous=False)
        )

    def total_out(self) -> int:
        return self._total_out


class KafkaTopicProducer(TopicProducer):
    def __init__(
        self,
        bootstrap: dict[str, Any],
        topic: str,
        producer_factory: Callable[[dict], Any] | None = None,
    ):
        self.topic = topic
        self._conf = dict(bootstrap)
        self._factory = producer_factory
        self._producer: Any = None
        self._total_in = 0

    async def start(self) -> None:
        if self._producer is None:
            if self._factory is not None:
                self._producer = self._factory(self._conf)
            else:
                self._producer = _kafka().Producer(self._conf)

    async def close(self) -> None:
        if self._producer is None:
            return
        producer, self._producer = self._producer, None
        await asyncio.get_running_loop().run_in_executor(None, producer.flush)

    async def write(self, record: Record) -> None:
        loop = asyncio.get_running_loop()
        done: asyncio.Future = loop.create_future()

        def _on_delivery(err: Any, msg: Any) -> None:
            # runs on the producer's poll thread
            if err is not None:
                loop.call_soon_threadsafe(
                    done.set_exception, RuntimeError(f"kafka produce failed: {err}")
                )
            else:
                loop.call_soon_threadsafe(done.set_result, None)

        key, value, headers = record_wire_payload(record)
        self._producer.produce(
            self.topic,
            value=value,
            key=key,
            headers=headers,
            on_delivery=_on_delivery,
        )
        # serve delivery callbacks until this write acks (durable append)
        while not done.done():
            await loop.run_in_executor(None, self._producer.poll, 0.05)
        await done
        self._total_in += 1

    def total_in(self) -> int:
        return self._total_in


class KafkaTopicReader(TopicReader):
    """Groupless reader: assigns all partitions at earliest/latest, never
    commits — each gateway session reads independently."""

    def __init__(
        self,
        bootstrap: dict[str, Any],
        topic: str,
        initial_position: str = "latest",
        consumer_factory: Callable[[dict], Any] | None = None,
    ):
        self.topic = topic
        self.initial_position = initial_position
        self._conf = {
            **bootstrap,
            "group.id": f"reader-{uuid.uuid4().hex}",
            "enable.auto.commit": False,
            "auto.offset.reset": (
                "earliest" if initial_position == "earliest" else "latest"
            ),
        }
        self._factory = consumer_factory
        self._consumer: Any = None

    async def start(self) -> None:
        kafka = _kafka()
        self._consumer = (
            self._factory(self._conf) if self._factory else kafka.Consumer(self._conf)
        )
        loop = asyncio.get_running_loop()
        # captured on the loop thread: close() nulls the field, and the
        # executor closure must not re-read it mid-flight (RACE801)
        consumer = self._consumer

        def _assign() -> None:
            md = consumer.list_topics(self.topic, timeout=10)
            topic_md = md.topics.get(self.topic)
            partitions = sorted(topic_md.partitions) if topic_md else [0]
            tps = []
            for p in partitions:
                lo, hi = consumer.get_watermark_offsets(
                    kafka.TopicPartition(self.topic, p), timeout=10
                )
                start = lo if self.initial_position == "earliest" else hi
                tps.append(kafka.TopicPartition(self.topic, p, start))
            consumer.assign(tps)

        await loop.run_in_executor(None, _assign)

    async def close(self) -> None:
        if self._consumer is None:
            return
        consumer, self._consumer = self._consumer, None
        await asyncio.get_running_loop().run_in_executor(None, consumer.close)

    async def read(self, timeout: float | None = None) -> list[Record]:
        loop = asyncio.get_running_loop()
        msgs = await loop.run_in_executor(
            None, self._consumer.consume, 64, timeout if timeout is not None else 0.5
        )
        out: list[Record] = []
        for msg in msgs or []:
            err = msg.error()
            if err:
                if KafkaTopicConsumer._is_partition_eof(err):
                    continue
                if getattr(err, "retriable", lambda: False)():
                    logger.warning("retriable reader error: %s", err)
                    continue
                raise RuntimeError(f"kafka reader error: {err}")
            out.append(kafka_message_to_record(msg))
        return out


class KafkaTopicAdmin(TopicAdmin):
    def __init__(self, bootstrap: dict[str, Any], admin_factory=None):
        self._conf = dict(bootstrap)
        self._factory = admin_factory

    def _admin(self) -> Any:
        if self._factory is not None:
            return self._factory(self._conf)
        from confluent_kafka.admin import AdminClient

        return AdminClient(self._conf)

    async def create_topic(
        self, name: str, partitions: int = 1, options: dict[str, Any] | None = None
    ) -> None:
        from confluent_kafka.admin import NewTopic

        admin = self._admin()
        replication = int((options or {}).get("replication-factor", 1))
        futures = admin.create_topics(
            [NewTopic(name, num_partitions=partitions, replication_factor=replication)]
        )
        await self._await_futures(futures, ignore="TOPIC_ALREADY_EXISTS")

    async def delete_topic(self, name: str) -> None:
        admin = self._admin()
        futures = admin.delete_topics([name])
        await self._await_futures(futures, ignore="UNKNOWN_TOPIC_OR_PART")

    @staticmethod
    async def _await_futures(futures: dict[str, Any], ignore: str) -> None:
        loop = asyncio.get_running_loop()
        for name, fut in futures.items():
            try:
                await loop.run_in_executor(None, fut.result)
            except Exception as e:  # noqa: BLE001 - client raises KafkaException
                if ignore not in str(e):
                    raise


class KafkaTopicConnectionsRuntime(TopicConnectionsRuntime):
    """``type: kafka`` streaming cluster.

    Configuration layout follows the reference's ``instance.yaml``
    (``examples/instances/kafka-docker.yaml:21-30``)::

        streamingCluster:
          type: kafka
          configuration:
            admin: {bootstrap.servers: "..."}
            consumer: {...}   # optional overrides
            producer: {...}   # optional overrides
    """

    def init(self, streaming_cluster_configuration: dict[str, Any]) -> None:
        super().init(streaming_cluster_configuration)
        conf = streaming_cluster_configuration or {}
        self.admin_conf = dict(conf.get("admin", {}))
        self.consumer_conf = {**self.admin_conf, **conf.get("consumer", {})}
        self.producer_conf = {**self.admin_conf, **conf.get("producer", {})}

    def create_consumer(self, agent_id: str, config: dict[str, Any]) -> TopicConsumer:
        return KafkaTopicConsumer(
            self.consumer_conf,
            topic=config["topic"],
            group=config.get("group", agent_id),
            poll_batch=int(config.get("poll-batch", 64)),
            poll_timeout=float(config.get("poll-timeout", 0.5)),
        )

    def create_producer(self, agent_id: str, config: dict[str, Any]) -> TopicProducer:
        return KafkaTopicProducer(self.producer_conf, topic=config["topic"])

    def create_reader(
        self, config: dict[str, Any], initial_position: str = "latest"
    ) -> TopicReader:
        return KafkaTopicReader(
            self.consumer_conf, config["topic"], initial_position
        )

    def create_topic_admin(self) -> TopicAdmin:
        return KafkaTopicAdmin(self.admin_conf)
