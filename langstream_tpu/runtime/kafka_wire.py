"""Kafka wire protocol, SDK-free — the broker-facing half of the Kafka
runtime without ``confluent_kafka`` (absent from this image).

Precedent: the repo's hand-rolled S3 sigv4 (``agents/s3_impl.py``) and CQL
v4 (``agents/cassandra_cql.py``) lanes — when the client library is the
missing piece, the wire protocol is our responsibility. Reference parity:
``langstream-kafka-runtime`` reaches real brokers through the Java client;
this module gives the Python runtime the same reach through the protocol
itself.

Scope (deliberate, documented): the NON-flexible protocol versions (no
compact/tagged fields — simple fixed structs), record batches v2 (magic 2,
CRC32C, zigzag-varint records — what every broker ≥ 0.11 speaks), and the
both consumer group modes: the "simple consumer" (OffsetCommit/OffsetFetch
with ``generation_id = -1`` + empty member id, static partition assignment
— replica i of n owns partitions ≡ i mod n, exact under StatefulSet
ordinals) and full dynamic membership (JoinGroup/SyncGroup/Heartbeat/
LeaveGroup with the leader-side range assignor and generation-fenced
commits — see :class:`~langstream_tpu.runtime.kafka_wire_runtime.GroupMembership`).

Security (what the reference's cloud instances need — e.g. its Astra
example sets ``security.protocol: SASL_SSL`` + ``sasl.mechanism: PLAIN``,
``examples/instances/astra.yaml:27-29``): TLS via ``ssl.SSLContext`` on the
connection, SASL PLAIN and SCRAM-SHA-256/-512 (RFC 5802, stdlib hmac/
hashlib) over SaslHandshake(v1) + SaslAuthenticate(v0). Fetch
decompression: gzip (stdlib) and zstd (zstandard, present in this image)
always; snappy always too — a pure-Python raw-block decoder
(:func:`_snappy_decompress_raw`) handles xerial-framed and bare blocks
when python-snappy is absent; lz4 raises a clear error naming the
missing codec library. Produce-side compression: optional gzip.

APIs: ApiVersions(0) Metadata(1) Produce(3) Fetch(4) ListOffsets(1)
FindCoordinator(1) OffsetCommit(2) OffsetFetch(1) JoinGroup(2)
Heartbeat(1) LeaveGroup(1) SyncGroup(1) SaslHandshake(1) ApiVersions(0)
CreateTopics(1) DeleteTopics(1) SaslAuthenticate(0).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import hmac
import logging
import re
import secrets
import ssl as ssl_module
import struct
import zlib
from dataclasses import dataclass, field
from typing import Any

logger = logging.getLogger(__name__)

# api keys
API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9
API_FIND_COORDINATOR = 10
API_JOIN_GROUP = 11
API_HEARTBEAT = 12
API_LEAVE_GROUP = 13
API_SYNC_GROUP = 14
API_SASL_HANDSHAKE = 17
API_API_VERSIONS = 18
API_CREATE_TOPICS = 19
API_DELETE_TOPICS = 20
API_SASL_AUTHENTICATE = 36

# error codes (subset)
ERR_NONE = 0
ERR_OFFSET_OUT_OF_RANGE = 1
ERR_UNKNOWN_TOPIC_OR_PARTITION = 3
ERR_NOT_LEADER = 6
ERR_COORDINATOR_NOT_AVAILABLE = 15
ERR_NOT_COORDINATOR = 16
ERR_ILLEGAL_GENERATION = 22
ERR_UNKNOWN_MEMBER_ID = 25
ERR_REBALANCE_IN_PROGRESS = 27
ERR_UNSUPPORTED_SASL_MECHANISM = 33
ERR_ILLEGAL_SASL_STATE = 34
ERR_TOPIC_ALREADY_EXISTS = 36
ERR_SASL_AUTHENTICATION_FAILED = 58

ERROR_NAMES = {
    ERR_OFFSET_OUT_OF_RANGE: "OFFSET_OUT_OF_RANGE",
    ERR_UNKNOWN_TOPIC_OR_PARTITION: "UNKNOWN_TOPIC_OR_PARTITION",
    ERR_NOT_LEADER: "NOT_LEADER_FOR_PARTITION",
    ERR_COORDINATOR_NOT_AVAILABLE: "COORDINATOR_NOT_AVAILABLE",
    ERR_NOT_COORDINATOR: "NOT_COORDINATOR",
    ERR_ILLEGAL_GENERATION: "ILLEGAL_GENERATION",
    ERR_UNKNOWN_MEMBER_ID: "UNKNOWN_MEMBER_ID",
    ERR_REBALANCE_IN_PROGRESS: "REBALANCE_IN_PROGRESS",
    ERR_TOPIC_ALREADY_EXISTS: "TOPIC_ALREADY_EXISTS",
}


class KafkaProtocolError(RuntimeError):
    def __init__(self, code: int, context: str):
        name = ERROR_NAMES.get(code, f"error {code}")
        super().__init__(f"kafka {name} ({code}): {context}")
        self.code = code


# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) — record batches checksum with this, not CRC32
# ---------------------------------------------------------------------------

_CRC32C_POLY = 0x82F63B78
_CRC32C_TABLE: list[int] = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _CRC32C_POLY if _c & 1 else _c >> 1
    _CRC32C_TABLE.append(_c)


def crc32c(data: bytes, crc: int = 0) -> int:
    crc = ~crc & 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC32C_TABLE[(crc ^ b) & 0xFF]
    return ~crc & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


class Writer:
    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def raw(self, b: bytes) -> "Writer":
        self._parts.append(b)
        return self

    def i8(self, v: int) -> "Writer":
        return self.raw(struct.pack(">b", v))

    def i16(self, v: int) -> "Writer":
        return self.raw(struct.pack(">h", v))

    def i32(self, v: int) -> "Writer":
        return self.raw(struct.pack(">i", v))

    def i64(self, v: int) -> "Writer":
        return self.raw(struct.pack(">q", v))

    def u32(self, v: int) -> "Writer":
        return self.raw(struct.pack(">I", v))

    def string(self, s: str | None) -> "Writer":
        if s is None:
            return self.i16(-1)
        b = s.encode("utf-8")
        return self.i16(len(b)).raw(b)

    def bytes_(self, b: bytes | None) -> "Writer":
        if b is None:
            return self.i32(-1)
        return self.i32(len(b)).raw(b)

    def array(self, items: list, write_item) -> "Writer":
        self.i32(len(items))
        for item in items:
            write_item(self, item)
        return self

    def varint(self, v: int) -> "Writer":
        # zigzag (python's arbitrary-precision >> keeps the sign, so the
        # classic (v << 1) ^ (v >> 63) works for any 64-bit value)
        z = ((v << 1) ^ (v >> 63)) & 0xFFFFFFFFFFFFFFFF
        while (z & ~0x7F) != 0:
            self.raw(bytes([(z & 0x7F) | 0x80]))
            z >>= 7
        return self.raw(bytes([z]))

    def done(self) -> bytes:
        return b"".join(self._parts)


class Reader:
    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def raw(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise EOFError(f"truncated kafka frame (want {n})")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def i8(self) -> int:
        return struct.unpack(">b", self.raw(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.raw(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.raw(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.raw(8))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self.raw(4))[0]

    def string(self) -> str | None:
        n = self.i16()
        return None if n < 0 else self.raw(n).decode("utf-8")

    def bytes_(self) -> bytes | None:
        n = self.i32()
        return None if n < 0 else self.raw(n)

    def array(self, read_item) -> list:
        n = self.i32()
        return [read_item(self) for _ in range(max(n, 0))]

    def varint(self) -> int:
        shift = 0
        z = 0
        while True:
            b = self.raw(1)[0]
            z |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        # un-zigzag
        return (z >> 1) ^ -(z & 1)

    def remaining(self) -> int:
        return len(self.data) - self.pos


# ---------------------------------------------------------------------------
# record batch v2 (magic 2)
# ---------------------------------------------------------------------------


@dataclass
class WireRecord:
    offset: int
    timestamp: int
    key: bytes | None
    value: bytes | None
    headers: list[tuple[str, bytes | None]] = field(default_factory=list)


def encode_record_batch(
    records: list[tuple[bytes | None, bytes | None, list[tuple[str, bytes | None]]]],
    base_timestamp: int,
    compression: str | None = None,
) -> bytes:
    """``records``: (key, value, headers) triples → one batch with base
    offset 0 (the broker rewrites offsets on append).

    ``compression``: None or ``"gzip"`` (the codec every broker and every
    client decompresses; producers wanting snappy/lz4/zstd on the wire
    should use the SDK lane)."""
    if compression not in (None, "gzip"):
        raise ValueError(
            f"produce compression {compression!r} not supported (gzip only)"
        )
    body = Writer()
    for i, (key, value, headers) in enumerate(records):
        rec = Writer()
        rec.raw(b"\x00")                      # attributes
        rec.varint(0)                         # timestampDelta
        rec.varint(i)                         # offsetDelta
        rec.varint(-1 if key is None else len(key))
        if key is not None:
            rec.raw(key)
        rec.varint(-1 if value is None else len(value))
        if value is not None:
            rec.raw(value)
        rec.varint(len(headers))
        for hk, hv in headers:
            hkb = hk.encode("utf-8")
            rec.varint(len(hkb))
            rec.raw(hkb)
            rec.varint(-1 if hv is None else len(hv))
            if hv is not None:
                rec.raw(hv)
        encoded = rec.done()
        body.varint(len(encoded)).raw(encoded)

    records_part = body.done()
    attributes = 0
    if compression == "gzip":
        attributes = 1
        records_part = _gzip_compress(records_part)
    # the part the CRC covers: attributes .. records
    crc_part = (
        Writer()
        .i16(attributes)                      # compression codec in bits 0-2
        .i32(len(records) - 1)                # lastOffsetDelta
        .i64(base_timestamp)                  # baseTimestamp
        .i64(base_timestamp)                  # maxTimestamp
        .i64(-1).i16(-1).i32(-1)              # producer id/epoch/baseSequence
        .i32(len(records))
        .raw(records_part)
        .done()
    )
    head = (
        Writer()
        .i64(0)                               # baseOffset (broker-assigned)
        .i32(4 + 1 + 4 + len(crc_part))       # batchLength from pLE onward
        .i32(-1)                              # partitionLeaderEpoch
        .i8(2)                                # magic
        .u32(crc32c(crc_part))
        .raw(crc_part)
    )
    return head.done()


def _gzip_compress(data: bytes) -> bytes:
    co = zlib.compressobj(6, zlib.DEFLATED, 16 + zlib.MAX_WBITS)
    return co.compress(data) + co.flush()


_CODEC_NAMES = {1: "gzip", 2: "snappy", 3: "lz4", 4: "zstd"}

#: xerial block-stream magic java snappy producers prepend
XERIAL_MAGIC = b"\x82SNAPPY\x00"


def _snappy_decompress_raw(data: bytes) -> bytes:
    """One raw snappy block, pure Python: a varint32 preamble with the
    uncompressed length, then tagged literal/copy elements (the format's
    only two element kinds). Fetch-path only — slow next to the C codec,
    but a consumer must read whatever an upstream java producer wrote,
    and this image has no python-snappy to lean on."""
    total = 0
    shift = 0
    i = 0
    while True:
        if i >= len(data):
            raise KafkaProtocolError(-1, "truncated snappy preamble")
        byte = data[i]
        i += 1
        total |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    out = bytearray()
    while i < len(data):
        tag = data[i]
        i += 1
        kind = tag & 3
        if kind == 0:  # literal
            length = tag >> 2
            if length >= 60:  # 60..63: length-1 in the next 1..4 LE bytes
                extra = length - 59
                length = int.from_bytes(data[i : i + extra], "little")
                i += extra
            length += 1
            if i + length > len(data):
                raise KafkaProtocolError(-1, "truncated snappy literal")
            out += data[i : i + length]
            i += length
            continue
        extra = 1 if kind == 1 else 2 if kind == 2 else 4
        if i + extra > len(data):
            raise KafkaProtocolError(-1, "truncated snappy copy")
        if kind == 1:  # copy, 1-byte offset: len 4..11, offset 11 bits
            length = ((tag >> 2) & 0x07) + 4
            offset = ((tag >> 5) << 8) | data[i]
        elif kind == 2:  # copy, 2-byte LE offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[i : i + 2], "little")
        else:  # copy, 4-byte LE offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[i : i + 4], "little")
        i += extra
        if offset == 0 or offset > len(out):
            raise KafkaProtocolError(-1, "corrupt snappy copy offset")
        start = len(out) - offset
        if offset >= length:
            out += out[start : start + length]
        else:  # overlapping copy = run-length repeat, byte at a time
            for j in range(length):
                out.append(out[start + j])
    if len(out) != total:
        raise KafkaProtocolError(
            -1, f"snappy length mismatch: got {len(out)}, preamble {total}"
        )
    return bytes(out)


try:
    # resolved once: a failed import is not cached, and re-attempting it
    # per fetch batch costs a sys.path scan in the consumer hot path
    import snappy as _python_snappy

    _snappy_block = _python_snappy.decompress
except ImportError:
    _snappy_block = _snappy_decompress_raw


def _snappy_decompress(data: bytes) -> bytes:
    """Snappy as Kafka ships it: java producers wrap raw blocks in xerial
    stream framing (magic + two version ints, then length-prefixed
    blocks); plain raw blocks also occur. python-snappy accelerates the
    per-block decode when present; the pure-Python decoder is the
    always-available fallback."""
    block = _snappy_block
    if data[:8] == XERIAL_MAGIC:
        r = Reader(data, 16)  # skip magic + version + compat
        chunks = []
        while r.remaining() > 0:
            chunks.append(block(r.raw(r.i32())))
        return b"".join(chunks)
    return block(data)


def decompress_records(codec: int, data: bytes) -> bytes:
    """Decompress a batch's records section. gzip rides stdlib zlib; zstd
    the ``zstandard`` package (present in this image); snappy the
    pure-Python raw-block decoder (python-snappy accelerates when
    installed); lz4 needs a library absent here — the error names the
    codec and the library so the operator knows exactly what the
    producer must change (or install)."""
    if codec == 1:  # gzip
        return zlib.decompress(data, 16 + zlib.MAX_WBITS)
    if codec == 4:  # zstd
        try:
            import zstandard
        except ImportError:
            raise KafkaProtocolError(
                -1, "zstd-compressed batch but the 'zstandard' package is "
                    "not installed"
            ) from None
        # streaming decompress: real producers (zstd-jni's output stream)
        # emit frames WITHOUT the content-size header field, which the
        # one-shot decompress() refuses
        return zstandard.ZstdDecompressor().decompressobj().decompress(data)
    if codec == 2:  # snappy (xerial framing or bare raw blocks)
        return _snappy_decompress(data)
    if codec == 3:  # lz4 (frame format)
        try:
            import lz4.frame
        except ImportError:
            raise KafkaProtocolError(
                -1,
                "lz4-compressed batch but the 'lz4' package is not "
                "installed in this image; reconfigure the producing side "
                "to gzip/zstd/snappy/none or install lz4",
            ) from None
        return lz4.frame.decompress(data)
    raise KafkaProtocolError(-1, f"unknown compression codec {codec}")


def decode_record_batches(data: bytes) -> list[WireRecord]:
    """Decode a record set (possibly several batches back to back);
    validates each batch's CRC32C. Compressed batches (gzip/zstd here;
    snappy/lz4 with the libraries installed) are decompressed before
    record parsing — see :func:`decompress_records`."""
    out: list[WireRecord] = []
    r = Reader(data)
    while r.remaining() >= 61:  # batch header floor
        base_offset = r.i64()
        batch_length = r.i32()
        if r.remaining() < batch_length:
            break  # broker may truncate the final batch mid-frame
        batch = Reader(r.raw(batch_length))
        batch.i32()                           # partitionLeaderEpoch
        magic = batch.i8()
        if magic != 2:
            raise KafkaProtocolError(-1, f"unsupported magic {magic}")
        crc = batch.u32()
        crc_part = batch.data[batch.pos:]
        if crc32c(crc_part) != crc:
            raise KafkaProtocolError(-1, "record batch CRC mismatch")
        attributes = batch.i16()
        if attributes & 0x20:
            # control batch (transaction commit/abort markers from other
            # producers on a shared cluster) — never application records
            continue
        batch.i32()                           # lastOffsetDelta
        base_ts = batch.i64()
        batch.i64()                           # maxTimestamp
        batch.i64(); batch.i16(); batch.i32() # producer id/epoch/seq
        count = batch.i32()
        codec = attributes & 0x07
        if codec:
            batch = Reader(
                decompress_records(codec, batch.raw(batch.remaining()))
            )
        for _ in range(count):
            length = batch.varint()
            rec = Reader(batch.raw(length))
            rec.i8()                          # attributes
            ts_delta = rec.varint()
            offset_delta = rec.varint()
            klen = rec.varint()
            key = rec.raw(klen) if klen >= 0 else None
            vlen = rec.varint()
            value = rec.raw(vlen) if vlen >= 0 else None
            headers = []
            for _h in range(rec.varint()):
                hklen = rec.varint()
                hk = rec.raw(hklen).decode("utf-8")
                hvlen = rec.varint()
                hv = rec.raw(hvlen) if hvlen >= 0 else None
                headers.append((hk, hv))
            out.append(WireRecord(
                offset=base_offset + offset_delta,
                timestamp=base_ts + ts_delta,
                key=key, value=value, headers=headers,
            ))
    return out


# ---------------------------------------------------------------------------
# consumer group protocol payloads ("consumer" embedded protocol v0) +
# the range assignor. These are the opaque bytes carried inside
# JoinGroup/SyncGroup — the broker never interprets them; the group LEADER
# member computes the assignment client-side, exactly like the Java client
# the reference's KafkaConsumerWrapper rides on.
# ---------------------------------------------------------------------------


def encode_subscription(topics: list[str]) -> bytes:
    """ConsumerProtocolSubscription v0: version, topics, user_data."""
    return (
        Writer()
        .i16(0)
        .array(sorted(topics), lambda w, t: w.string(t))
        .bytes_(None)
        .done()
    )


def decode_subscription(data: bytes) -> list[str]:
    r = Reader(data)
    r.i16()                                   # version
    return [r.string() for _ in range(r.i32())]


def encode_assignment(parts: dict[str, list[int]]) -> bytes:
    """ConsumerProtocolAssignment v0: version, [(topic, [partition])],
    user_data."""
    w = Writer().i16(0)

    def _topic(wr: Writer, item) -> None:
        topic, plist = item
        wr.string(topic)
        wr.array(sorted(plist), lambda w2, p: w2.i32(p))

    w.array(sorted(parts.items()), _topic)
    return w.bytes_(None).done()


def decode_assignment(data: bytes) -> dict[str, list[int]]:
    if not data:
        return {}
    r = Reader(data)
    r.i16()                                   # version
    out: dict[str, list[int]] = {}
    for _ in range(r.i32()):
        topic = r.string()
        out[topic] = [r.i32() for _ in range(r.i32())]
    return out


def range_assign(
    subscriptions: dict[str, list[str]],
    partitions_by_topic: dict[str, list[int]],
) -> dict[str, dict[str, list[int]]]:
    """The Java client's RangeAssignor: per topic, subscribed members in
    member-id order each take a contiguous range of the partition list,
    with the first ``parts % members`` members taking one extra."""
    out: dict[str, dict[str, list[int]]] = {m: {} for m in subscriptions}
    for topic, partitions in sorted(partitions_by_topic.items()):
        members = sorted(m for m, topics in subscriptions.items() if topic in topics)
        if not members:
            continue
        parts = sorted(partitions)
        quotient, remainder = divmod(len(parts), len(members))
        pos = 0
        for index, member in enumerate(members):
            take = quotient + (1 if index < remainder else 0)
            if take:
                out[member][topic] = parts[pos : pos + take]
            pos += take
    return out


# ---------------------------------------------------------------------------
# connection + client
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# security: TLS + SASL (PLAIN, SCRAM-SHA-256/-512)
# ---------------------------------------------------------------------------


_JAAS_FIELD = re.compile(r'(\w+)\s*=\s*"((?:[^"\\]|\\.)*)"')


@dataclass
class KafkaSecurity:
    """Connection security for the wire client, mirroring the Java client
    properties the reference's instances carry (``security.protocol``,
    ``sasl.mechanism``, ``sasl.jaas.config``)."""

    protocol: str = "PLAINTEXT"  # PLAINTEXT | SSL | SASL_PLAINTEXT | SASL_SSL
    mechanism: str = "PLAIN"     # PLAIN | SCRAM-SHA-256 | SCRAM-SHA-512
    username: str | None = None
    password: str | None = None
    ssl_cafile: str | None = None
    ssl_verify: bool = True           # False → CERT_NONE (+ no hostname)
    ssl_check_hostname: bool = True   # False → chain verified, name not
                                      # (Java: empty endpoint-identification
                                      # algorithm disables ONLY the name check)
    ssl_context: ssl_module.SSLContext | None = None  # overrides the above

    @property
    def use_tls(self) -> bool:
        return self.protocol in ("SSL", "SASL_SSL")

    @property
    def use_sasl(self) -> bool:
        return self.protocol in ("SASL_PLAINTEXT", "SASL_SSL")

    def build_ssl_context(self) -> ssl_module.SSLContext:
        if self.ssl_context is not None:
            return self.ssl_context
        ctx = ssl_module.create_default_context(cafile=self.ssl_cafile)
        if not self.ssl_check_hostname or not self.ssl_verify:
            ctx.check_hostname = False
        if not self.ssl_verify:
            ctx.verify_mode = ssl_module.CERT_NONE
        return ctx

    @classmethod
    def from_client_properties(
        cls, props: dict[str, Any]
    ) -> "KafkaSecurity | None":
        """Java-client-style properties → KafkaSecurity (None = plaintext).

        Credentials come from ``sasl.jaas.config`` (the reference's style:
        ``PlainLoginModule required username="..." password="...";``) or
        the flatter ``sasl.username``/``sasl.password`` pair."""
        protocol = str(props.get("security.protocol", "PLAINTEXT")).upper()
        if protocol == "PLAINTEXT":
            return None
        if protocol not in ("SSL", "SASL_PLAINTEXT", "SASL_SSL"):
            raise ValueError(
                f"security.protocol {protocol!r} not supported "
                "(PLAINTEXT|SSL|SASL_PLAINTEXT|SASL_SSL)"
            )
        username = props.get("sasl.username")
        password = props.get("sasl.password")
        jaas = props.get("sasl.jaas.config")
        if jaas and (username is None or password is None):
            fields = {
                # JAAS quoted values escape \" and \\ — unescape them, as
                # the Java client does
                k: re.sub(r"\\(.)", r"\1", v)
                for k, v in _JAAS_FIELD.findall(str(jaas))
            }
            username = username or fields.get("username")
            password = password or fields.get("password")
        mechanism = str(props.get("sasl.mechanism", "PLAIN")).upper()
        if protocol.startswith("SASL"):
            if mechanism not in ("PLAIN", "SCRAM-SHA-256", "SCRAM-SHA-512"):
                raise ValueError(
                    f"sasl.mechanism {mechanism!r} not supported "
                    "(PLAIN|SCRAM-SHA-256|SCRAM-SHA-512)"
                )
            if username is None or password is None:
                raise ValueError(
                    f"{protocol} requires credentials: set sasl.jaas.config "
                    "(username=\"..\" password=\"..\") or "
                    "sasl.username/sasl.password"
                )
        # "" disables endpoint identification (the HOSTNAME check only —
        # the chain is still verified) in the Java client; honour it
        ident = props.get("ssl.endpoint.identification.algorithm")
        verify = str(props.get("ssl.verify", "true")).lower() not in (
            "false", "0", "no"
        )
        return cls(
            protocol=protocol,
            mechanism=mechanism,
            username=username,
            password=password,
            ssl_cafile=props.get("ssl.ca.location"),
            ssl_verify=verify,
            ssl_check_hostname=ident != "",
        )


def _scram_escape(name: str) -> str:
    return name.replace("=", "=3D").replace(",", "=2C")


class ScramClient:
    """RFC 5802 client for SCRAM-SHA-256/-512 (stdlib only). Stateful over
    the three-message exchange; verifies the server signature so a broker
    that doesn't know the password is detected, not just the reverse."""

    def __init__(self, mechanism: str, username: str, password: str,
                 nonce: str | None = None):
        self._hash = {
            "SCRAM-SHA-256": hashlib.sha256,
            "SCRAM-SHA-512": hashlib.sha512,
        }[mechanism]
        self._hash_name = self._hash().name
        self.username = username
        self.password = password.encode("utf-8")
        self.nonce = nonce or secrets.token_urlsafe(24)
        self._client_first_bare = ""
        self._auth_message = b""
        self._salted = b""

    def client_first(self) -> bytes:
        self._client_first_bare = (
            f"n={_scram_escape(self.username)},r={self.nonce}"
        )
        return ("n,," + self._client_first_bare).encode("utf-8")

    def client_final(self, server_first: bytes) -> bytes:
        text = server_first.decode("utf-8")
        fields = dict(p.split("=", 1) for p in text.split(","))
        server_nonce, salt, iters = fields["r"], fields["s"], int(fields["i"])
        if not server_nonce.startswith(self.nonce):
            raise KafkaProtocolError(
                ERR_SASL_AUTHENTICATION_FAILED,
                "SCRAM server nonce does not extend the client nonce",
            )
        self._salted = hashlib.pbkdf2_hmac(
            self._hash_name, self.password, base64.b64decode(salt), iters
        )
        client_key = hmac.new(self._salted, b"Client Key", self._hash).digest()
        stored_key = self._hash(client_key).digest()
        without_proof = f"c=biws,r={server_nonce}"
        self._auth_message = ",".join(
            [self._client_first_bare, text, without_proof]
        ).encode("utf-8")
        signature = hmac.new(stored_key, self._auth_message, self._hash).digest()
        proof = bytes(a ^ b for a, b in zip(client_key, signature))
        final = f"{without_proof},p={base64.b64encode(proof).decode()}"
        return final.encode("utf-8")

    def verify_server_final(self, server_final: bytes) -> None:
        fields = dict(
            p.split("=", 1) for p in server_final.decode("utf-8").split(",")
        )
        if "e" in fields:
            raise KafkaProtocolError(
                ERR_SASL_AUTHENTICATION_FAILED, f"SCRAM: {fields['e']}"
            )
        server_key = hmac.new(self._salted, b"Server Key", self._hash).digest()
        expected = hmac.new(server_key, self._auth_message, self._hash).digest()
        if not hmac.compare_digest(
            base64.b64decode(fields["v"]), expected
        ):
            raise KafkaProtocolError(
                ERR_SASL_AUTHENTICATION_FAILED,
                "SCRAM server signature mismatch (broker does not know "
                "the password?)",
            )


class _Conn:
    """One broker connection; requests are serialized (correlation ids
    still checked). The runtime's per-agent access pattern is sequential."""

    def __init__(self, host: str, port: int, client_id: str,
                 security: KafkaSecurity | None = None):
        self.host, self.port = host, port
        self.client_id = client_id
        self.security = security
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._correlation = 0
        self._lock = asyncio.Lock()

    async def connect(self) -> None:
        sec = self.security
        if sec is not None and sec.use_tls:
            ctx = sec.build_ssl_context()
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port, ssl=ctx,
                server_hostname=self.host if ctx.check_hostname else None,
            )
        else:
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port
            )
        if sec is not None and sec.use_sasl:
            try:
                await self._sasl_authenticate(sec)
            except BaseException:
                self._writer.close()
                self._writer = self._reader = None
                raise

    async def _roundtrip(self, api_key: int, version: int,
                         payload: bytes) -> Reader:
        """One request/response WITHOUT the lock (connect-time SASL runs
        inside ``call``'s lock already)."""
        self._correlation += 1
        cid = self._correlation
        header = (
            Writer()
            .i16(api_key).i16(version).i32(cid)
            .string(self.client_id)
            .done()
        )
        frame = header + payload
        self._writer.write(struct.pack(">i", len(frame)) + frame)
        await self._writer.drain()
        (size,) = struct.unpack(">i", await self._reader.readexactly(4))
        body = await self._reader.readexactly(size)
        r = Reader(body)
        got = r.i32()
        if got != cid:
            raise KafkaProtocolError(
                -1, f"correlation mismatch (sent {cid}, got {got})"
            )
        return r

    async def _sasl_call(self, token: bytes) -> bytes:
        """SaslAuthenticate v0 exchange; raises on broker auth errors."""
        r = await self._roundtrip(
            API_SASL_AUTHENTICATE, 0, Writer().bytes_(token).done()
        )
        error = r.i16()
        message = r.string()
        auth_bytes = r.bytes_() or b""
        if error:
            raise KafkaProtocolError(
                error, f"SASL authentication failed: {message or 'denied'}"
            )
        return auth_bytes

    async def _sasl_authenticate(self, sec: KafkaSecurity) -> None:
        r = await self._roundtrip(
            API_SASL_HANDSHAKE, 1, Writer().string(sec.mechanism).done()
        )
        error = r.i16()
        if error:
            supported = r.array(lambda rr: rr.string())
            raise KafkaProtocolError(
                error,
                f"broker rejected SASL mechanism {sec.mechanism} "
                f"(supports: {supported})",
            )
        if sec.mechanism == "PLAIN":
            token = (
                b"\x00" + sec.username.encode("utf-8")
                + b"\x00" + sec.password.encode("utf-8")
            )
            await self._sasl_call(token)
        else:  # SCRAM
            scram = ScramClient(sec.mechanism, sec.username, sec.password)
            server_first = await self._sasl_call(scram.client_first())
            server_final = await self._sasl_call(
                scram.client_final(server_first)
            )
            scram.verify_server_final(server_final)

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (OSError, ConnectionError):
                pass
            self._writer = None

    async def call(self, api_key: int, version: int, payload: bytes) -> Reader:
        async with self._lock:
            if self._writer is None:
                await self.connect()
            try:
                return await self._roundtrip(api_key, version, payload)
            except (OSError, asyncio.IncompleteReadError, ConnectionError):
                # brokers drop idle connections (connections.max.idle.ms):
                # a dead socket must not poison every later call — drop it
                # so the next call redials (and re-authenticates)
                try:
                    self._writer.close()
                except Exception as e:
                    logger.debug("closing dead broker socket failed: %s", e)
                self._writer = self._reader = None
                raise


@dataclass
class PartitionMeta:
    leader: int
    error: int = 0


class KafkaWireClient:
    """Metadata-aware client: routes produce/fetch to partition leaders,
    refreshes metadata on NOT_LEADER / UNKNOWN_TOPIC errors."""

    def __init__(self, bootstrap: str, client_id: str = "langstream-tpu",
                 security: KafkaSecurity | None = None):
        host, _, port = bootstrap.partition(":")
        self.bootstrap = (host, int(port or 9092))
        self.client_id = client_id
        self.security = security
        self._conns: dict[int, _Conn] = {}
        self._bootstrap_conn: _Conn | None = None
        self.brokers: dict[int, tuple[str, int]] = {}
        self.topics: dict[str, dict[int, PartitionMeta]] = {}
        self._group_coordinators: dict[str, int] = {}  # group -> node id

    async def _boot(self) -> _Conn:
        if self._bootstrap_conn is None:
            self._bootstrap_conn = _Conn(
                *self.bootstrap, self.client_id, security=self.security
            )
            await self._bootstrap_conn.connect()
        return self._bootstrap_conn

    async def close(self) -> None:
        for conn in self._conns.values():
            await conn.close()
        self._conns.clear()
        if self._bootstrap_conn is not None:
            await self._bootstrap_conn.close()
            self._bootstrap_conn = None

    async def _node(self, node_id: int) -> _Conn:
        if node_id not in self._conns:
            host, port = self.brokers.get(node_id, self.bootstrap)
            conn = _Conn(host, port, self.client_id, security=self.security)
            await conn.connect()
            self._conns[node_id] = conn
        return self._conns[node_id]

    # -- apis --------------------------------------------------------------

    async def api_versions(self) -> dict[int, tuple[int, int]]:
        conn = await self._boot()
        r = await conn.call(API_API_VERSIONS, 0, b"")
        error = r.i16()
        if error:
            raise KafkaProtocolError(error, "ApiVersions")
        out = {}
        for _ in range(r.i32()):
            key, lo, hi = r.i16(), r.i16(), r.i16()
            out[key] = (lo, hi)
        return out

    async def refresh_metadata(self, topics: list[str] | None = None) -> None:
        conn = await self._boot()
        w = Writer()
        if topics is None:
            w.i32(-1)
        else:
            w.array(topics, lambda wr, t: wr.string(t))
        r = await conn.call(API_METADATA, 1, w.done())
        self.brokers = {}
        for _ in range(r.i32()):
            node, host, port = r.i32(), r.string(), r.i32()
            r.string()  # rack
            self.brokers[node] = (host, port)
        r.i32()  # controller id
        for _ in range(r.i32()):
            terr = r.i16()
            tname = r.string()
            r.raw(1)  # is_internal bool
            parts: dict[int, PartitionMeta] = {}
            for _p in range(r.i32()):
                perr = r.i16()
                pid = r.i32()
                leader = r.i32()
                r.array(lambda rr: rr.i32())  # replicas
                r.array(lambda rr: rr.i32())  # isr
                parts[pid] = PartitionMeta(leader=leader, error=perr)
            if terr == ERR_NONE:
                self.topics[tname] = parts
            elif tname in self.topics:
                del self.topics[tname]

    async def partitions_for(self, topic: str) -> list[int]:
        if topic not in self.topics:
            await self.refresh_metadata([topic])
        if topic not in self.topics:
            raise KafkaProtocolError(ERR_UNKNOWN_TOPIC_OR_PARTITION, topic)
        return sorted(self.topics[topic])

    async def _leader_conn(self, topic: str, partition: int) -> _Conn:
        if topic not in self.topics or partition not in self.topics[topic]:
            await self.refresh_metadata([topic])
        meta = self.topics.get(topic, {}).get(partition)
        if meta is None:
            raise KafkaProtocolError(
                ERR_UNKNOWN_TOPIC_OR_PARTITION, f"{topic}[{partition}]"
            )
        return await self._node(meta.leader)

    async def produce(
        self,
        topic: str,
        partition: int,
        records: list[tuple[bytes | None, bytes | None, list[tuple[str, bytes | None]]]],
        timestamp_ms: int,
        acks: int = -1,
        timeout_ms: int = 30000,
        compression: str | None = None,
    ) -> int:
        """→ base offset assigned by the broker."""
        batch = encode_record_batch(records, timestamp_ms, compression)
        for attempt in range(2):
            conn = await self._leader_conn(topic, partition)
            w = (
                Writer()
                .string(None)                 # transactional id
                .i16(acks)
                .i32(timeout_ms)
            )

            def _topic(wr: Writer, t: str) -> None:
                wr.string(t)
                wr.array([partition], lambda w2, p: (
                    w2.i32(p), w2.bytes_(batch)
                ))

            w.array([topic], _topic)
            r = await conn.call(API_PRODUCE, 3, w.done())
            # exactly one topic/partition was sent; parse linearly
            r.i32()                           # topic count (1)
            r.string()
            r.i32()                           # partition count (1)
            r.i32()                           # partition
            error = r.i16()
            base_offset = r.i64()
            r.i64()                           # log append time
            if (
                error in (ERR_NOT_LEADER, ERR_UNKNOWN_TOPIC_OR_PARTITION)
                and attempt == 0
            ):
                await self.refresh_metadata([topic])
                continue
            if error:
                raise KafkaProtocolError(error, f"produce {topic}[{partition}]")
            return base_offset
        raise KafkaProtocolError(-1, f"produce {topic}[{partition}] kept failing")

    async def fetch(
        self,
        topic: str,
        partition: int,
        offset: int,
        max_wait_ms: int = 500,
        max_bytes: int = 4 * 1024 * 1024,
    ) -> tuple[list[WireRecord], int]:
        """→ (records from ``offset`` onward, high watermark)."""
        conn = await self._leader_conn(topic, partition)
        w = (
            Writer()
            .i32(-1)                          # replica id
            .i32(max_wait_ms)
            .i32(1)                           # min bytes
            .i32(max_bytes)
            .i8(0)                            # isolation: read uncommitted
        )

        def _topic(wr: Writer, t: str) -> None:
            wr.string(t)
            wr.array([partition], lambda w2, p: (
                w2.i32(p), w2.i64(offset), w2.i32(max_bytes)
            ))

        w.array([topic], _topic)
        r = await conn.call(API_FETCH, 4, w.done())
        r.i32()                               # throttle
        records: list[WireRecord] = []
        high_watermark = -1
        for _ in range(r.i32()):
            r.string()
            for _p in range(r.i32()):
                r.i32()                       # partition
                error = r.i16()
                high_watermark = r.i64()
                r.i64()                       # last stable offset
                r.array(lambda rr: (rr.i64(), rr.i64()))  # aborted txns
                record_set = r.bytes_() or b""
                if error:
                    raise KafkaProtocolError(
                        error, f"fetch {topic}[{partition}] @{offset}"
                    )
                records.extend(
                    rec for rec in decode_record_batches(record_set)
                    if rec.offset >= offset
                )
        return records, high_watermark

    async def list_offsets(
        self, topic: str, partition: int, timestamp: int
    ) -> int:
        """timestamp -1 = latest (log end), -2 = earliest."""
        conn = await self._leader_conn(topic, partition)
        w = Writer().i32(-1)

        def _topic(wr: Writer, t: str) -> None:
            wr.string(t)
            wr.array([partition], lambda w2, p: (w2.i32(p), w2.i64(timestamp)))

        w.array([topic], _topic)
        r = await conn.call(API_LIST_OFFSETS, 1, w.done())
        for _ in range(r.i32()):
            r.string()
            for _p in range(r.i32()):
                r.i32()
                error = r.i16()
                r.i64()                       # timestamp
                off = r.i64()
                if error:
                    raise KafkaProtocolError(
                        error, f"list_offsets {topic}[{partition}]"
                    )
                return off
        raise KafkaProtocolError(-1, "empty ListOffsets response")

    async def find_coordinator(self, group: str) -> _Conn:
        """Group-coordinator connection, cached per group: the heartbeat
        hot path must not pay a FindCoordinator round trip every beat.
        Invalidated by :meth:`_call_coordinator` on NOT_COORDINATOR or a
        dead connection."""
        node = self._group_coordinators.get(group)
        if node is not None:
            return await self._node(node)
        conn = await self._boot()
        w = Writer().string(group).i8(0)
        r = await conn.call(API_FIND_COORDINATOR, 1, w.done())
        r.i32()                               # throttle
        error = r.i16()
        r.string()                            # error message
        node, host, port = r.i32(), r.string(), r.i32()
        if error:
            raise KafkaProtocolError(error, f"find_coordinator {group}")
        self.brokers.setdefault(node, (host, port))
        self._group_coordinators[group] = node
        return await self._node(node)

    async def _call_coordinator(
        self, group: str, api_key: int, version: int, payload: bytes
    ) -> Reader:
        """One coordinator RPC with a single re-lookup retry when the
        cached coordinator moved or the connection died (the group-API
        analogue of the NOT_LEADER metadata refresh on produce/fetch)."""
        for attempt in (0, 1):
            conn = await self.find_coordinator(group)
            try:
                return await conn.call(api_key, version, payload)
            except (ConnectionError, OSError, asyncio.IncompleteReadError):
                self._group_coordinators.pop(group, None)
                if attempt:
                    raise
        raise AssertionError("unreachable")

    @staticmethod
    def _check_coordinator_error(error: int, group: str, context: str) -> None:
        if error:
            raise KafkaProtocolError(error, context)

    def _invalidate_coordinator_on(self, group: str, error: int) -> None:
        if error in (ERR_NOT_COORDINATOR, ERR_COORDINATOR_NOT_AVAILABLE):
            self._group_coordinators.pop(group, None)

    async def offset_commit(
        self, group: str, offsets: dict[tuple[str, int], int]
    ) -> None:
        """Simple-consumer commit — exactly the grouped commit with
        generation -1 and an empty member id."""
        await self.offset_commit_grouped(group, -1, "", offsets)

    async def offset_fetch(
        self, group: str, topic: str, partitions: list[int]
    ) -> dict[int, int]:
        """→ {partition: committed offset} (-1 = no commit)."""
        w = Writer().string(group)

        def _topic(wr: Writer, t: str) -> None:
            wr.string(t)
            wr.array(partitions, lambda w2, p: w2.i32(p))

        w.array([topic], _topic)
        r = await self._call_coordinator(group, API_OFFSET_FETCH, 1, w.done())
        out: dict[int, int] = {}
        for _ in range(r.i32()):
            r.string()
            for _p in range(r.i32()):
                partition = r.i32()
                offset = r.i64()
                r.string()                    # metadata
                error = r.i16()
                if error:
                    raise KafkaProtocolError(
                        error, f"offset_fetch {group} {topic}[{partition}]"
                    )
                out[partition] = offset
        return out

    # -- consumer group membership (JoinGroup v2 / SyncGroup v1 /
    #    Heartbeat v1 / LeaveGroup v1 — the non-flexible versions, like
    #    every other API here) ----------------------------------------------

    async def join_group(
        self,
        group: str,
        member_id: str,
        topics: list[str],
        session_timeout_ms: int = 10000,
        rebalance_timeout_ms: int = 30000,
    ) -> dict[str, Any]:
        """One JoinGroup round trip. Returns {generation, member_id, leader,
        protocol, members: {member_id: [topics]} (leader only)}."""
        w = (
            Writer()
            .string(group)
            .i32(session_timeout_ms)
            .i32(rebalance_timeout_ms)
            .string(member_id)
            .string("consumer")
            .array(
                [("range", encode_subscription(topics))],
                lambda wr, p: (wr.string(p[0]), wr.bytes_(p[1])),
            )
        )
        r = await self._call_coordinator(group, API_JOIN_GROUP, 2, w.done())
        r.i32()                               # throttle
        error = r.i16()
        generation = r.i32()
        protocol = r.string()
        leader = r.string()
        own_id = r.string()
        members: dict[str, list[str]] = {}
        for _ in range(r.i32()):
            mid = r.string()
            meta = r.bytes_()
            members[mid] = decode_subscription(meta) if meta else []
        if error:
            self._invalidate_coordinator_on(group, error)
            raise KafkaProtocolError(error, f"join_group {group}")
        return {
            "generation": generation,
            "member_id": own_id,
            "leader": leader,
            "protocol": protocol,
            "members": members,
        }

    async def sync_group(
        self,
        group: str,
        generation: int,
        member_id: str,
        assignments: dict[str, dict[str, list[int]]] | None = None,
    ) -> dict[str, list[int]]:
        """Leader passes the computed assignments; followers pass None.
        Returns this member's own {topic: [partitions]}."""
        encoded = [
            (mid, encode_assignment(parts))
            for mid, parts in (assignments or {}).items()
        ]
        w = (
            Writer()
            .string(group)
            .i32(generation)
            .string(member_id)
            .array(encoded, lambda wr, p: (wr.string(p[0]), wr.bytes_(p[1])))
        )
        r = await self._call_coordinator(group, API_SYNC_GROUP, 1, w.done())
        r.i32()                               # throttle
        error = r.i16()
        assignment = r.bytes_()
        if error:
            self._invalidate_coordinator_on(group, error)
            raise KafkaProtocolError(error, f"sync_group {group}")
        return decode_assignment(assignment or b"")

    async def heartbeat(self, group: str, generation: int, member_id: str) -> None:
        w = Writer().string(group).i32(generation).string(member_id)
        r = await self._call_coordinator(group, API_HEARTBEAT, 1, w.done())
        r.i32()                               # throttle
        error = r.i16()
        if error:
            self._invalidate_coordinator_on(group, error)
            raise KafkaProtocolError(error, f"heartbeat {group}")

    async def leave_group(self, group: str, member_id: str) -> None:
        w = Writer().string(group).string(member_id)
        r = await self._call_coordinator(group, API_LEAVE_GROUP, 1, w.done())
        r.i32()                               # throttle
        error = r.i16()
        if error and error != ERR_UNKNOWN_MEMBER_ID:
            raise KafkaProtocolError(error, f"leave_group {group}")

    async def offset_commit_grouped(
        self,
        group: str,
        generation: int,
        member_id: str,
        offsets: dict[tuple[str, int], int],
    ) -> None:
        """Commit as a dynamic group member: the coordinator fences stale
        generations (ILLEGAL_GENERATION) so a zombie replica that missed a
        rebalance cannot clobber the new owner's progress."""
        by_topic: dict[str, list[tuple[int, int]]] = {}
        for (topic, partition), offset in offsets.items():
            by_topic.setdefault(topic, []).append((partition, offset))
        w = (
            Writer()
            .string(group)
            .i32(generation)
            .string(member_id)
            .i64(-1)                          # retention
        )

        def _topic(wr: Writer, item) -> None:
            topic, parts = item
            wr.string(topic)
            wr.array(parts, lambda w2, po: (
                w2.i32(po[0]), w2.i64(po[1]), w2.string(None)
            ))

        w.array(list(by_topic.items()), _topic)
        r = await self._call_coordinator(group, API_OFFSET_COMMIT, 2, w.done())
        for _ in range(r.i32()):
            topic = r.string()
            for _p in range(r.i32()):
                partition = r.i32()
                error = r.i16()
                if error:
                    self._invalidate_coordinator_on(group, error)
                    raise KafkaProtocolError(
                        error, f"offset_commit {group} {topic}[{partition}]"
                    )

    async def create_topic(
        self, topic: str, partitions: int = 1, replication: int = 1,
        exist_ok: bool = True,
    ) -> None:
        conn = await self._boot()
        w = Writer()

        def _topic(wr: Writer, t: str) -> None:
            wr.string(t)
            wr.i32(partitions)
            wr.i16(replication)
            wr.i32(0)                         # assignments
            wr.i32(0)                         # configs
        w.array([topic], _topic)
        w.i32(30000)                          # timeout
        w.raw(b"\x00")                        # validate_only = false
        r = await conn.call(API_CREATE_TOPICS, 1, w.done())
        for _ in range(r.i32()):
            r.string()
            error = r.i16()
            r.string()                        # error message
            if error == ERR_TOPIC_ALREADY_EXISTS and exist_ok:
                continue
            if error:
                raise KafkaProtocolError(error, f"create_topic {topic}")
        await self.refresh_metadata([topic])

    async def delete_topic(self, topic: str) -> None:
        conn = await self._boot()
        w = Writer().array([topic], lambda wr, t: wr.string(t)).i32(30000)
        r = await conn.call(API_DELETE_TOPICS, 1, w.done())
        r.i32()                               # throttle
        for _ in range(r.i32()):
            r.string()
            error = r.i16()
            if error and error != ERR_UNKNOWN_TOPIC_OR_PARTITION:
                raise KafkaProtocolError(error, f"delete_topic {topic}")
        self.topics.pop(topic, None)
