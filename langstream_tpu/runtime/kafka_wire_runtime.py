"""Kafka topic runtime over the in-tree wire protocol (no client library).

The ``type: kafka`` streaming cluster resolves here when
``confluent_kafka`` is not importable (or when ``client: wire`` is forced):
the same topic SPI — consumer with contiguous-prefix commits, producer with
serializer inference, position-addressed reader, admin, dead-letter via the
base class — backed by :mod:`.kafka_wire` instead of an SDK.

Partition ownership defaults to STATIC: replica ``i`` of ``n`` owns
partitions ``p ≡ i (mod n)`` (``replica-index`` / ``num-replicas`` in the
consumer config, or the pod's ordinal env). Under the k8s runtime each
replica is a StatefulSet ordinal, so assignment is exact and
rebalance-free. ``assignment: dynamic`` opts into the wire-spoken consumer
group protocol instead — JoinGroup/SyncGroup/Heartbeat/LeaveGroup with the
leader-side range assignor and generation-fenced commits
(:class:`GroupMembership`) — matching the Java client's group membership
the reference rides (``KafkaConsumerWrapper.java:41`` implements
``ConsumerRebalanceListener``). The contiguous-commit semantics are
identical in both modes and shared via :class:`ContiguousOffsetTracker`.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any

from langstream_tpu.api.record import Record, SimpleRecord, now_millis
from langstream_tpu.api.topics import (
    OFFSET_HEADER,
    TopicAdmin,
    TopicConsumer,
    TopicConnectionsRuntime,
    TopicOffset,
    TopicProducer,
    TopicReader,
)
from langstream_tpu.runtime.kafka_broker import (
    ContiguousOffsetTracker,
    HEADER_KINDS_HEADER,
    KEY_KIND_HEADER,
    VALUE_KIND_HEADER,
    _KIND_HEADERS,
    deserialize_datum,
    record_wire_payload,
)
from langstream_tpu.runtime.kafka_wire import (
    ERR_ILLEGAL_GENERATION,
    ERR_OFFSET_OUT_OF_RANGE,
    ERR_REBALANCE_IN_PROGRESS,
    ERR_UNKNOWN_MEMBER_ID,
    KafkaProtocolError,
    KafkaSecurity,
    KafkaWireClient,
    WireRecord,
    range_assign,
)

logger = logging.getLogger(__name__)

_GROUP_ERRORS = (
    ERR_ILLEGAL_GENERATION,
    ERR_REBALANCE_IN_PROGRESS,
    ERR_UNKNOWN_MEMBER_ID,
)


def _wire_record_to_record(topic: str, rec: WireRecord) -> Record:
    import json

    kinds = {k: v for k, v in rec.headers if k in _KIND_HEADERS}
    hkinds: dict[str, str] = {}
    raw = kinds.get(HEADER_KINDS_HEADER)
    if raw is not None:
        try:
            hkinds = json.loads(raw.decode())
        except (json.JSONDecodeError, UnicodeDecodeError):
            pass
    headers = tuple(
        (k, None if hkinds.get(k) == "null" else deserialize_datum(v, hkinds.get(k)))
        for k, v in rec.headers
        if k not in _KIND_HEADERS
    ) + ((OFFSET_HEADER, TopicOffset(topic, 0, rec.offset)),)
    return SimpleRecord(
        value=deserialize_datum(rec.value, kinds.get(VALUE_KIND_HEADER)),
        key=deserialize_datum(rec.key, kinds.get(KEY_KIND_HEADER)),
        headers=headers,
        origin=topic,
        timestamp=rec.timestamp if rec.timestamp > 0 else now_millis(),
    )




class GroupMembership:
    """Client half of the consumer group protocol: join → (leader computes
    the range assignment) → sync → heartbeat cadence; rejoin on the group
    error codes. This is the dynamic-rebalance lane the reference rides the
    Java client for (``KafkaConsumerWrapper.java:41`` implements
    ``ConsumerRebalanceListener``) — here it is spoken on the wire."""

    def __init__(
        self,
        client: KafkaWireClient,
        group: str,
        topics: list[str],
        session_timeout_ms: int = 10000,
        heartbeat_interval_s: float = 0.5,
    ):
        self.client = client
        self.group = group
        self.topics = topics
        self.session_timeout_ms = session_timeout_ms
        self.heartbeat_interval_s = heartbeat_interval_s
        self.member_id = ""
        self.generation = -1
        self.assignment: dict[str, list[int]] = {}
        self._last_heartbeat = 0.0
        self.rebalance_needed = False
        self._hb_task: asyncio.Task | None = None

    def _ensure_heartbeat_task(self) -> None:
        """Heartbeats must keep flowing while the owner is busy processing
        a batch — a session-timeout's worth of silence gets the member
        evicted by a real coordinator (the Java client heartbeats from a
        background thread for the same reason). ``_Conn.call`` serializes
        on a lock, so this task can share the coordinator connection."""
        if self._hb_task is not None and not self._hb_task.done():
            return

        async def beat() -> None:
            while True:
                await asyncio.sleep(self.heartbeat_interval_s)
                if self.rebalance_needed or not self.member_id:
                    continue                   # owner must rejoin first
                self._last_heartbeat = time.monotonic()
                try:
                    await self.client.heartbeat(
                        self.group, self.generation, self.member_id
                    )
                except KafkaProtocolError as e:
                    if e.code in _GROUP_ERRORS:
                        if e.code == ERR_UNKNOWN_MEMBER_ID:
                            self.member_id = ""
                        self.rebalance_needed = True
                    # other codes: transient — next beat retries
                except (ConnectionError, OSError):
                    pass                       # redial happens on next call

        self._hb_task = asyncio.get_running_loop().create_task(beat())

    async def join(self) -> dict[str, list[int]]:
        """Run join+sync rounds until the group is stable; returns this
        member's {topic: [partitions]}."""
        while True:
            try:
                info = await self.client.join_group(
                    self.group, self.member_id, self.topics,
                    session_timeout_ms=self.session_timeout_ms,
                )
            except KafkaProtocolError as e:
                if e.code == ERR_UNKNOWN_MEMBER_ID:
                    self.member_id = ""      # fenced: restart as a new member
                    continue
                raise
            self.member_id = info["member_id"]
            self.generation = info["generation"]
            assignments = None
            if info["leader"] == self.member_id:
                subscribed = sorted(
                    {t for topics in info["members"].values() for t in topics}
                )
                partitions = {
                    t: await self.client.partitions_for(t) for t in subscribed
                }
                assignments = range_assign(info["members"], partitions)
            try:
                self.assignment = await self.client.sync_group(
                    self.group, self.generation, self.member_id, assignments
                )
            except KafkaProtocolError as e:
                if e.code in _GROUP_ERRORS:
                    if e.code == ERR_UNKNOWN_MEMBER_ID:
                        self.member_id = ""
                    continue                 # another round started — rejoin
                raise
            self._last_heartbeat = time.monotonic()
            self.rebalance_needed = False
            self._ensure_heartbeat_task()
            return self.assignment

    async def heartbeat_if_due(self) -> bool:
        """False → the group is rebalancing and the caller must rejoin."""
        if self.rebalance_needed:
            return False
        now = time.monotonic()
        if now - self._last_heartbeat < self.heartbeat_interval_s:
            return True
        self._last_heartbeat = now
        try:
            await self.client.heartbeat(self.group, self.generation, self.member_id)
            return True
        except KafkaProtocolError as e:
            if e.code in _GROUP_ERRORS:
                if e.code == ERR_UNKNOWN_MEMBER_ID:
                    self.member_id = ""
                return False
            raise

    async def leave(self) -> None:
        if self._hb_task is not None:
            self._hb_task.cancel()
            try:
                await self._hb_task
            except asyncio.CancelledError:
                pass
            except Exception as e:  # noqa: BLE001
                logger.debug("heartbeat task errored at leave: %s", e)
            self._hb_task = None
        if self.member_id:
            try:
                await self.client.leave_group(self.group, self.member_id)
            except (KafkaProtocolError, ConnectionError, OSError):
                pass
            self.member_id = ""
            self.generation = -1


class WireKafkaTopicConsumer(TopicConsumer):
    """Group consumer with contiguous-prefix commits. Two assignment modes:
    ``static`` (replica ``i`` of ``n`` owns partitions ``p ≡ i mod n`` —
    exact under StatefulSet ordinals, rebalance-free) and ``dynamic`` (the
    wire group protocol: join/sync/heartbeat with generation-fenced
    commits, reference parity with the Java client's group membership)."""

    def __init__(
        self,
        bootstrap: str,
        topic: str,
        group: str,
        replica_index: int = 0,
        num_replicas: int = 1,
        poll_timeout_ms: int = 500,
        assignment: str = "static",
        session_timeout_ms: int = 10000,
        security: KafkaSecurity | None = None,
    ):
        self.topic = topic
        self.group = group
        self.replica_index = replica_index
        self.num_replicas = max(1, num_replicas)
        self.poll_timeout_ms = poll_timeout_ms
        self.client = KafkaWireClient(bootstrap, security=security)
        self.tracker = ContiguousOffsetTracker()
        self.membership = (
            GroupMembership(
                self.client, group, [topic],
                session_timeout_ms=session_timeout_ms,
            )
            if assignment == "dynamic"
            else None
        )
        self._positions: dict[int, int] = {}
        self._committed: dict[int, int] = {}
        self._out = 0
        self._rebalances = 0

    async def start(self) -> None:
        if self.membership is not None:
            assignment = await self.membership.join()
            await self._adopt_partitions(assignment.get(self.topic, []))
        else:
            partitions = await self.client.partitions_for(self.topic)
            mine = [
                p for p in partitions
                if p % self.num_replicas == self.replica_index % self.num_replicas
            ]
            await self._adopt_partitions(mine)

    async def _adopt_partitions(self, mine: list[int]) -> None:
        """(Re)initialize positions from the committed offsets. On a
        rebalance, in-flight uncommitted records of lost partitions are
        simply redelivered to their new owner — the at-least-once contract
        (parity: ``KafkaConsumerWrapper.java:82-112`` logs exactly this)."""
        self._positions = {}
        self._committed = {}
        self.tracker = ContiguousOffsetTracker()
        committed = await self.client.offset_fetch(self.group, self.topic, mine)
        for p in mine:
            start = committed.get(p, -1)
            if start < 0:
                start = await self.client.list_offsets(self.topic, p, -2)
            self._positions[p] = start
            self._committed[p] = start
            self.tracker.start_partition(self.topic, p, start)

    async def close(self) -> None:
        if self.membership is not None:
            await self.membership.leave()
        await self.client.close()

    async def read(self) -> list[Record]:
        if self.membership is not None:
            if not await self.membership.heartbeat_if_due():
                # group is rebalancing: rejoin and adopt the new assignment;
                # uncommitted in-flight records of partitions that moved are
                # redelivered to their new owner (at-least-once)
                assignment = await self.membership.join()
                await self._adopt_partitions(assignment.get(self.topic, []))
                self._rebalances += 1
        out: list[Record] = []
        partitions = sorted(self._positions)
        if not partitions:
            # owning no partitions is a normal group state (more members
            # than partitions): sleep a poll instead of busy-spinning the
            # caller's read loop at 100% CPU
            await asyncio.sleep(self.poll_timeout_ms / 1000.0)
            return out
        # every owned partition is polled every read — no partition can
        # starve behind a busy sibling (per-key ordering is per-partition,
        # so interleaving partitions in one batch is safe); the wait budget
        # splits across partitions so an empty one can't eat the whole poll
        wait_ms = max(50, self.poll_timeout_ms // max(1, len(partitions)))
        for p in partitions:
            pos = self._positions[p]
            try:
                recs, _hw = await self.client.fetch(
                    self.topic, p, pos, max_wait_ms=wait_ms
                )
            except KafkaProtocolError as e:
                if e.code == ERR_OFFSET_OUT_OF_RANGE:
                    # log truncated under us (retention): resume from the
                    # new earliest AND re-seed the commit tracker — a stale
                    # tracker position would wedge the contiguous prefix
                    # and no commit would ever be written again
                    new_start = await self.client.list_offsets(
                        self.topic, p, -2
                    )
                    self._positions[p] = new_start
                    self._committed[p] = new_start
                    self.tracker.start_partition(self.topic, p, new_start)
                    continue
                raise
            for rec in recs:
                record = _wire_record_to_record(self.topic, rec)
                # rewrite the offset header with the true partition
                headers = tuple(
                    (k, TopicOffset(self.topic, p, rec.offset))
                    if k == OFFSET_HEADER else (k, v)
                    for k, v in record.headers
                )
                record = SimpleRecord(
                    value=record.value, key=record.key, headers=headers,
                    origin=self.topic, timestamp=record.timestamp,
                )
                self.tracker.delivered(self.topic, p, rec.offset)
                out.append(record)
                self._positions[p] = rec.offset + 1
        self._out += len(out)
        return out

    async def commit(self, records: list[Record]) -> None:
        to_commit: dict[tuple[str, int], int] = {}
        for record in records:
            offset = record.header(OFFSET_HEADER)
            if not isinstance(offset, TopicOffset):
                continue
            next_pos = self.tracker.acknowledge(
                offset.topic, offset.partition, offset.offset
            )
            if next_pos is not None and next_pos > self._committed.get(
                offset.partition, -1
            ):
                self._committed[offset.partition] = next_pos
                to_commit[(offset.topic, offset.partition)] = next_pos
        if not to_commit:
            return
        if self.membership is not None:
            try:
                await self.client.offset_commit_grouped(
                    self.group,
                    self.membership.generation,
                    self.membership.member_id,
                    to_commit,
                )
            except KafkaProtocolError as e:
                if e.code in _GROUP_ERRORS:
                    # fenced: these partitions moved in a rebalance this
                    # member hasn't processed yet. Dropping the commit is
                    # the correct at-least-once outcome — the new owner
                    # resumes from the last successful commit and the
                    # records are redelivered there; the next read()
                    # rejoins.
                    return
                raise
        else:
            await self.client.offset_commit(self.group, to_commit)

    def total_out(self) -> int:
        return self._out


class WireKafkaTopicProducer(TopicProducer):
    def __init__(self, bootstrap: str, topic: str,
                 security: KafkaSecurity | None = None,
                 compression: str | None = None):
        self.topic = topic
        self.client = KafkaWireClient(bootstrap, security=security)
        self.compression = compression
        self._partitions: list[int] = []
        self._rr = 0
        self._in = 0

    async def start(self) -> None:
        self._partitions = await self.client.partitions_for(self.topic)

    async def close(self) -> None:
        await self.client.close()

    def _partition_for(self, key: bytes | None) -> int:
        if not self._partitions:
            return 0
        if key is not None:
            # stable key → partition mapping preserves per-key ordering
            import zlib

            return self._partitions[
                zlib.crc32(key) % len(self._partitions)
            ]
        self._rr += 1
        return self._partitions[self._rr % len(self._partitions)]

    async def write(self, record: Record) -> None:
        key, value, headers = record_wire_payload(record)
        partition = self._partition_for(key)
        await self.client.produce(
            self.topic, partition, [(key, value, headers)],
            timestamp_ms=record.timestamp or now_millis(),
            compression=self.compression,
        )
        self._in += 1

    def total_in(self) -> int:
        return self._in


class WireKafkaTopicReader(TopicReader):
    """Position-addressed reader (gateway consume side); no group."""

    def __init__(self, bootstrap: str, topic: str, initial_position: str,
                 security: KafkaSecurity | None = None):
        self.topic = topic
        self.initial_position = initial_position
        self.client = KafkaWireClient(bootstrap, security=security)
        self._positions: dict[int, int] = {}

    async def start(self) -> None:
        ts = -2 if self.initial_position == "earliest" else -1
        for p in await self.client.partitions_for(self.topic):
            self._positions[p] = await self.client.list_offsets(
                self.topic, p, ts
            )

    async def close(self) -> None:
        await self.client.close()

    async def read(self, timeout: float | None = None) -> list[Record]:
        out: list[Record] = []
        wait_ms = int((timeout or 0.2) * 1000)
        for p, pos in list(self._positions.items()):
            recs, _hw = await self.client.fetch(
                self.topic, p, pos, max_wait_ms=wait_ms
            )
            for rec in recs:
                out.append(_wire_record_to_record(self.topic, rec))
                self._positions[p] = rec.offset + 1
        return out


class WireKafkaTopicAdmin(TopicAdmin):
    def __init__(self, bootstrap: str,
                 security: KafkaSecurity | None = None):
        self.bootstrap = bootstrap
        self.security = security

    async def create_topic(
        self, name: str, partitions: int = 1,
        options: dict[str, Any] | None = None,
    ) -> None:
        opts = options or {}
        client = KafkaWireClient(self.bootstrap, security=self.security)
        try:
            await client.create_topic(
                name,
                partitions=int(opts.get("partitions", partitions)),
                # same option the SDK-backed admin honors — dropping it
                # would silently create RF-1 topics on production clusters
                replication=int(opts.get("replication-factor", 1)),
                exist_ok=True,
            )
        finally:
            await client.close()

    async def delete_topic(self, name: str) -> None:
        client = KafkaWireClient(self.bootstrap, security=self.security)
        try:
            await client.delete_topic(name)
        finally:
            await client.close()


def _replica_hints(config: dict[str, Any]) -> tuple[int, int]:
    """Replica identity for static assignment. The agent runner passes
    ``replica-index``/``num-replicas`` explicitly; the env fallback mirrors
    the pod entrypoint's identity derivation (``runtime/pod.py``:
    ``LS_LOGICAL_REPLICA``, else the StatefulSet ordinal in
    ``LS_POD_NAME``)."""
    replica = config.get("replica-index")
    replicas = config.get("num-replicas")
    if replica is None:
        logical = os.environ.get("LS_LOGICAL_REPLICA")
        if logical is not None:
            replica = logical
        else:
            from langstream_tpu.runtime.pod import pod_ordinal

            replica = pod_ordinal(os.environ.get("LS_POD_NAME"))
    if replicas is None:
        replicas = os.environ.get("LS_NUM_REPLICAS", "1")
    return int(replica), int(replicas)


class KafkaTopicConnectionsRuntimeSelector(TopicConnectionsRuntime):
    """The ``type: kafka`` front door: picks the backend from the
    ``client`` config key — ``wire`` (in-tree protocol, static
    assignment), ``sdk`` (confluent_kafka, dynamic group rebalance), or
    the default ``auto`` (sdk when importable, else wire)."""

    def init(self, streaming_cluster_configuration: dict[str, Any]) -> None:
        super().init(streaming_cluster_configuration)
        conf = streaming_cluster_configuration or {}
        choice = str(conf.get("client", "auto")).lower()
        if choice not in ("auto", "wire", "sdk"):
            raise ValueError(
                f"streamingCluster kafka client {choice!r} not supported "
                "(auto|wire|sdk)"
            )
        use_sdk = False
        if choice in ("auto", "sdk"):
            try:
                import confluent_kafka  # noqa: F401

                use_sdk = True
            except ImportError:
                if choice == "sdk":
                    raise RuntimeError(
                        "streamingCluster requests client: sdk but "
                        "confluent_kafka is not installed; use client: wire"
                    ) from None
        if use_sdk:
            from langstream_tpu.runtime.kafka_broker import (
                KafkaTopicConnectionsRuntime,
            )

            self._backend: TopicConnectionsRuntime = (
                KafkaTopicConnectionsRuntime()
            )
        else:
            self._backend = WireKafkaTopicConnectionsRuntime()
        self._backend.init(conf)

    def create_consumer(self, agent_id: str, config: dict[str, Any]) -> TopicConsumer:
        return self._backend.create_consumer(agent_id, config)

    def create_producer(self, agent_id: str, config: dict[str, Any]) -> TopicProducer:
        return self._backend.create_producer(agent_id, config)

    def create_reader(
        self, config: dict[str, Any], initial_position: str = "latest"
    ) -> TopicReader:
        return self._backend.create_reader(config, initial_position)

    def create_topic_admin(self) -> TopicAdmin:
        return self._backend.create_topic_admin()

    def create_deadletter_producer(
        self, agent_id: str, config: dict[str, Any]
    ) -> TopicProducer | None:
        return self._backend.create_deadletter_producer(agent_id, config)

    async def close(self) -> None:
        await self._backend.close()


class WireKafkaTopicConnectionsRuntime(TopicConnectionsRuntime):
    """``type: kafka`` over the in-tree wire client. Same configuration
    layout as the SDK-backed runtime (``admin: {bootstrap.servers: ...}``)."""

    def init(self, streaming_cluster_configuration: dict[str, Any]) -> None:
        super().init(streaming_cluster_configuration)
        conf = streaming_cluster_configuration or {}
        admin = conf.get("admin", {})
        self.bootstrap = (
            admin.get("bootstrap.servers")
            or conf.get("bootstrap")
            or "127.0.0.1:9092"
        ).split(",")[0]
        # SASL/TLS: the reference's cloud instances put the Java client
        # security properties in the same admin/consumer/producer maps
        # (examples/instances/astra.yaml) — merge, admin lowest precedence
        props = {
            **admin,
            **conf.get("consumer", {}),
            **conf.get("producer", {}),
        }
        self.security = KafkaSecurity.from_client_properties(props)
        ctype = str(
            conf.get("producer", {}).get("compression.type", "none")
        ).lower()
        if ctype in ("none", ""):
            self.compression = None
        elif ctype == "gzip":
            self.compression = "gzip"
        else:
            raise ValueError(
                f"wire lane produce compression.type {ctype!r} not "
                "supported (none|gzip); consumption decompresses "
                "gzip/zstd regardless"
            )

    def create_consumer(self, agent_id: str, config: dict[str, Any]) -> TopicConsumer:
        replica, replicas = _replica_hints(config)
        return WireKafkaTopicConsumer(
            self.bootstrap,
            topic=config["topic"],
            group=config.get("group", agent_id),
            replica_index=replica,
            num_replicas=replicas,
            poll_timeout_ms=int(float(config.get("poll-timeout", 0.5)) * 1000),
            assignment=str(config.get("assignment", "static")).lower(),
            session_timeout_ms=int(config.get("session-timeout-ms", 10000)),
            security=self.security,
        )

    def create_producer(self, agent_id: str, config: dict[str, Any]) -> TopicProducer:
        return WireKafkaTopicProducer(
            self.bootstrap, topic=config["topic"], security=self.security,
            compression=self.compression,
        )

    def create_reader(
        self, config: dict[str, Any], initial_position: str = "latest"
    ) -> TopicReader:
        return WireKafkaTopicReader(
            self.bootstrap, config["topic"], initial_position,
            security=self.security,
        )

    def create_topic_admin(self) -> TopicAdmin:
        return WireKafkaTopicAdmin(self.bootstrap, security=self.security)
