"""In-process application runner: the dev-mode / test workhorse.

Parity: ``LocalApplicationRunner`` + the runtime-tester
(``langstream-runtime-tester/.../tester/LocalApplicationRunner.java:55,179``):
parse → plan → setup topics/assets → run every agent replica as an in-process
task against the in-memory broker; expose produce/consume helpers the way the
reference's tests use gateways. This is also the fixture our integration
tests build on (SURVEY.md §4: AbstractApplicationRunner role).
"""

from __future__ import annotations

import asyncio
from pathlib import Path
from typing import Any

from langstream_tpu.api.application import Application
from langstream_tpu.api.execution_plan import ExecutionPlan
from langstream_tpu.api.record import Record, make_record
from langstream_tpu.api.topics import TopicConnectionsRuntimeRegistry
from langstream_tpu.core.deployer import ApplicationDeployer
from langstream_tpu.core.parser import build_application_from_directory
from langstream_tpu.runtime.runner import AgentRunner


class LocalApplicationRunner:
    def __init__(
        self,
        application: Application,
        application_id: str = "app",
        state_dir: Path | None = None,
    ):
        self.application = application
        self.application_id = application_id
        self.state_dir = state_dir
        self.deployer = ApplicationDeployer()
        self.plan: ExecutionPlan | None = None
        self.runners: list[AgentRunner] = []
        self._topics_runtime = None

    @classmethod
    def from_directory(
        cls,
        directory: Path | str,
        instance: str | Path | None = None,
        secrets: str | Path | None = None,
        application_id: str = "app",
        state_dir: Path | None = None,
    ) -> "LocalApplicationRunner":
        app = build_application_from_directory(directory, instance, secrets)
        return cls(app, application_id=application_id, state_dir=state_dir)

    async def start(self) -> ExecutionPlan:
        self.plan = self.deployer.create_implementation(
            self.application_id, self.application
        )
        await self.deployer.setup(self.plan)
        for node in self.plan.agents.values():
            for replica in range(max(1, node.resources.parallelism)):
                runner = AgentRunner(
                    self.plan, node, replica=replica, state_dir=self.state_dir
                )
                await runner.start()
                self.runners.append(runner)
        return self.plan

    async def stop(self) -> None:
        errors = []
        for runner in self.runners:
            try:
                await runner.stop()
            except Exception as e:
                errors.append(e)
        self.runners.clear()
        if self._topics_runtime is not None:
            await self._topics_runtime.close()
        if errors:
            raise errors[0]

    # ---- client-side helpers (what gateways do over WS) ------------------

    def _runtime(self):
        if self._topics_runtime is None:
            streaming = self.application.instance.streaming_cluster
            self._topics_runtime = TopicConnectionsRuntimeRegistry.get_runtime(
                {"type": streaming.type, "configuration": streaming.configuration}
            )
        return self._topics_runtime

    async def produce(
        self,
        topic: str,
        value: Any,
        key: Any = None,
        headers: dict[str, Any] | None = None,
    ) -> None:
        producer = self._runtime().create_producer("local-client", {"topic": topic})
        await producer.start()
        await producer.write(make_record(value=value, key=key, headers=headers))
        await producer.close()

    def reader(self, topic: str, position: str = "earliest"):
        return self._runtime().create_reader({"topic": topic}, initial_position=position)

    async def wait_for_messages(
        self, topic: str, count: int, timeout: float = 10.0, position: str = "earliest"
    ) -> list[Record]:
        """Test helper (parity: AbstractKafkaApplicationRunner.waitForMessages)."""
        reader = self.reader(topic, position)
        await reader.start()
        got: list[Record] = []
        deadline = asyncio.get_event_loop().time() + timeout
        while len(got) < count:
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                raise TimeoutError(
                    f"expected {count} records on {topic!r}, got {len(got)}"
                )
            got.extend(await reader.read(timeout=min(0.5, remaining)))
        await reader.close()
        return got

    def agent_info(self) -> list[dict[str, Any]]:
        return [r.info() for r in self.runners]

    async def __aenter__(self) -> "LocalApplicationRunner":
        await self.start()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.stop()
