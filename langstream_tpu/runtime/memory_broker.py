"""In-process partitioned broker with Kafka-class offset semantics.

This is the first-party streaming substrate (the reference embeds a real
Kafka for its dev mode; our dev/default transport is in-tree). Semantics
mirror what the agent runtime relies on in the reference:

- partitioned topics; records hash-routed by key (sticky round-robin when
  keyless);
- consumer *groups* with partition assignment and rebalance on member
  join/leave (parity: ``KafkaConsumerWrapper`` implements
  ``ConsumerRebalanceListener``, ``KafkaConsumerWrapper.java:41``);
- **out-of-order acknowledgement with contiguous-prefix commit**: a consumer
  may commit delivered offsets in any order; the group's committed position
  on a partition only advances over the longest contiguous prefix
  (``KafkaConsumerWrapper.java:203``) — uncommitted gaps are redelivered to
  the next consumer after a restart/rebalance (at-least-once);
- position-addressed *readers* for the gateway consume path (no group).
"""

from __future__ import annotations

import asyncio
import itertools
import threading
from typing import Any

from langstream_tpu.api.record import Record, SimpleRecord
from langstream_tpu.api.topics import (
    OFFSET_HEADER,
    TopicAdmin,
    TopicConsumer,
    TopicConnectionsRuntime,
    TopicOffset,
    TopicProducer,
    TopicReader,
)


class _Partition:
    def __init__(self, topic: str, index: int):
        self.topic = topic
        self.index = index
        self.records: list[Record] = []

    def append(self, record: Record) -> int:
        self.records.append(record)
        return len(self.records) - 1


class _GroupPartitionState:
    """Per (group, partition): committed position + in-flight offsets."""

    def __init__(self) -> None:
        self.committed = 0  # next offset to deliver after restart
        self.delivered = 0  # next offset to hand out
        self.acked: set[int] = set()

    def ack(self, offset: int) -> None:
        self.acked.add(offset)
        while self.committed in self.acked:
            self.acked.discard(self.committed)
            self.committed += 1

    def reset_to_committed(self) -> None:
        self.delivered = self.committed
        self.acked.clear()


class MemoryTopic:
    def __init__(self, name: str, partitions: int = 1):
        self.name = name
        self.partitions = [_Partition(name, i) for i in range(partitions)]
        self._rr = itertools.cycle(range(partitions))
        self.groups: dict[str, dict[int, _GroupPartitionState]] = {}
        self.memberships: dict[str, "_GroupMembership"] = {}
        self.cond = asyncio.Condition()

    def group_state(self, group: str, partition: int) -> _GroupPartitionState:
        g = self.groups.setdefault(group, {})
        if partition not in g:
            g[partition] = _GroupPartitionState()
        return g[partition]

    def route(self, record: Record) -> _Partition:
        if record.key is not None:
            key = record.key
            if isinstance(key, (dict, list)):
                key = str(key)
            return self.partitions[hash(key) % len(self.partitions)]
        return self.partitions[next(self._rr)]


class MemoryBroker:
    """One named broker cluster: a set of topics shared by every runtime
    instance in this process that names the same cluster."""

    _clusters: dict[str, "MemoryBroker"] = {}
    _clusters_lock = threading.Lock()

    def __init__(self) -> None:
        self.topics: dict[str, MemoryTopic] = {}
        self._lock = threading.Lock()

    @classmethod
    def get(cls, cluster_name: str) -> "MemoryBroker":
        with cls._clusters_lock:
            if cluster_name not in cls._clusters:
                cls._clusters[cluster_name] = cls()
            return cls._clusters[cluster_name]

    @classmethod
    def reset(cls, cluster_name: str | None = None) -> None:
        with cls._clusters_lock:
            if cluster_name is None:
                cls._clusters.clear()
            else:
                cls._clusters.pop(cluster_name, None)

    def topic(self, name: str, create: bool = True, partitions: int = 1) -> MemoryTopic:
        with self._lock:
            if name not in self.topics:
                if not create:
                    raise KeyError(f"unknown topic {name!r}")
                self.topics[name] = MemoryTopic(name, partitions)
            return self.topics[name]

    async def publish(self, topic_name: str, record: Record) -> TopicOffset:
        topic = self.topic(topic_name)
        async with topic.cond:
            partition = topic.route(record)
            stamped = SimpleRecord(
                value=record.value,
                key=record.key,
                headers=record.headers,
                origin=topic_name,
                timestamp=record.timestamp,
            )
            offset = partition.append(stamped)
            topic.cond.notify_all()
        return TopicOffset(topic_name, partition.index, offset)


class _GroupMembership:
    """Static round-robin partition assignment among live group members."""

    def __init__(self, topic: MemoryTopic, group: str):
        self.topic = topic
        self.group = group
        self.members: list["MemoryTopicConsumer"] = []

    def join(self, consumer: "MemoryTopicConsumer") -> None:
        self.members.append(consumer)
        self._rebalance()

    def leave(self, consumer: "MemoryTopicConsumer") -> None:
        if consumer in self.members:
            self.members.remove(consumer)
        self._rebalance()

    def _rebalance(self) -> None:
        n = len(self.members)
        for m in self.members:
            m.assigned = []
        if n == 0:
            return
        for i, partition in enumerate(self.topic.partitions):
            member = self.members[i % n]
            member.assigned.append(partition.index)
            # redelivery from the committed position for newly-assigned parts
            self.topic.group_state(self.group, partition.index).reset_to_committed()


def _membership(topic: MemoryTopic, group: str) -> _GroupMembership:
    # stored on the topic itself, so dropping the broker drops everything
    if group not in topic.memberships:
        topic.memberships[group] = _GroupMembership(topic, group)
    return topic.memberships[group]


class MemoryTopicConsumer(TopicConsumer):
    def __init__(self, broker: MemoryBroker, topic_name: str, group: str,
                 poll_batch: int = 64, poll_timeout: float = 0.5):
        self.broker = broker
        self.topic_name = topic_name
        self.group = group
        self.poll_batch = poll_batch
        self.poll_timeout = poll_timeout
        self.assigned: list[int] = []
        self._total_out = 0
        self._started = False

    async def start(self) -> None:
        if self._started:
            return
        self._started = True
        topic = self.broker.topic(self.topic_name)
        async with topic.cond:
            _membership(topic, self.group).join(self)

    async def close(self) -> None:
        if not self._started:
            return
        topic = self.broker.topic(self.topic_name)
        async with topic.cond:
            _membership(topic, self.group).leave(self)
        self._started = False

    async def read(self) -> list[Record]:
        topic = self.broker.topic(self.topic_name)
        async with topic.cond:
            batch = self._poll_locked(topic)
            if batch:
                return batch
            try:
                await asyncio.wait_for(topic.cond.wait(), timeout=self.poll_timeout)
            except asyncio.TimeoutError:
                return []
            return self._poll_locked(topic)

    def _poll_locked(self, topic: MemoryTopic) -> list[Record]:
        batch: list[Record] = []
        for pi in self.assigned:
            partition = topic.partitions[pi]
            state = topic.group_state(self.group, pi)
            while state.delivered < len(partition.records) and len(batch) < self.poll_batch:
                record = partition.records[state.delivered]
                stamped = record.with_headers(
                    {OFFSET_HEADER: TopicOffset(self.topic_name, pi, state.delivered)}
                )
                batch.append(stamped)
                state.delivered += 1
        self._total_out += len(batch)
        return batch

    async def commit(self, records: list[Record]) -> None:
        topic = self.broker.topic(self.topic_name)
        async with topic.cond:
            for record in records:
                offset: TopicOffset | None = record.header(OFFSET_HEADER)
                if offset is None or offset.topic != self.topic_name:
                    continue
                topic.group_state(self.group, offset.partition).ack(offset.offset)

    def total_out(self) -> int:
        return self._total_out


class MemoryTopicProducer(TopicProducer):
    def __init__(self, broker: MemoryBroker, topic_name: str):
        self.broker = broker
        self.topic_name = topic_name
        self._total_in = 0

    async def write(self, record: Record) -> None:
        # strip transport headers before re-publishing
        if record.header(OFFSET_HEADER) is not None:
            record = SimpleRecord(
                value=record.value,
                key=record.key,
                headers=tuple(
                    (k, v) for k, v in record.headers if k != OFFSET_HEADER
                ),
                origin=record.origin,
                timestamp=record.timestamp,
            )
        await self.broker.publish(self.topic_name, record)
        self._total_in += 1

    def total_in(self) -> int:
        return self._total_in


class MemoryTopicReader(TopicReader):
    """Position-addressed reader over all partitions (gateway consume)."""

    def __init__(self, broker: MemoryBroker, topic_name: str, initial_position: str):
        self.broker = broker
        self.topic_name = topic_name
        self.initial_position = initial_position
        self.positions: dict[int, int] = {}

    async def start(self) -> None:
        topic = self.broker.topic(self.topic_name)
        async with topic.cond:
            for p in topic.partitions:
                self.positions[p.index] = (
                    0 if self.initial_position == "earliest" else len(p.records)
                )

    async def read(self, timeout: float | None = 0.5) -> list[Record]:
        topic = self.broker.topic(self.topic_name)
        async with topic.cond:
            batch = self._poll_locked(topic)
            if batch or timeout == 0:
                return batch
            try:
                await asyncio.wait_for(topic.cond.wait(), timeout=timeout)
            except asyncio.TimeoutError:
                return []
            return self._poll_locked(topic)

    def _poll_locked(self, topic: MemoryTopic) -> list[Record]:
        batch: list[Record] = []
        for p in topic.partitions:
            pos = self.positions.setdefault(p.index, len(p.records))
            while pos < len(p.records):
                batch.append(
                    p.records[pos].with_headers(
                        {OFFSET_HEADER: TopicOffset(self.topic_name, p.index, pos)}
                    )
                )
                pos += 1
            self.positions[p.index] = pos
        return batch


class MemoryTopicAdmin(TopicAdmin):
    def __init__(self, broker: MemoryBroker):
        self.broker = broker

    async def create_topic(
        self, name: str, partitions: int = 1, options: dict[str, Any] | None = None
    ) -> None:
        self.broker.topic(name, create=True, partitions=partitions)

    async def delete_topic(self, name: str) -> None:
        with self.broker._lock:
            self.broker.topics.pop(name, None)


class MemoryTopicConnectionsRuntime(TopicConnectionsRuntime):
    def init(self, streaming_cluster_configuration: dict[str, Any]) -> None:
        super().init(streaming_cluster_configuration)
        cluster = (streaming_cluster_configuration or {}).get("cluster", "default")
        self.broker = MemoryBroker.get(cluster)

    def create_consumer(self, agent_id: str, config: dict[str, Any]) -> TopicConsumer:
        return MemoryTopicConsumer(
            self.broker,
            topic_name=config["topic"],
            group=config.get("group", agent_id),
            poll_batch=int(config.get("poll-batch", 64)),
            poll_timeout=float(config.get("poll-timeout", 0.5)),
        )

    def create_producer(self, agent_id: str, config: dict[str, Any]) -> TopicProducer:
        return MemoryTopicProducer(self.broker, topic_name=config["topic"])

    def create_reader(
        self, config: dict[str, Any], initial_position: str = "latest"
    ) -> TopicReader:
        return MemoryTopicReader(self.broker, config["topic"], initial_position)

    def create_topic_admin(self) -> TopicAdmin:
        return MemoryTopicAdmin(self.broker)
