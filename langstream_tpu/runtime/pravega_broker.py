"""Pravega streaming runtime (gated on the ``pravega_client`` binding).

Parity: ``langstream-pravega-runtime`` —
``PravegaTopicConnectionsRuntimeProvider.java`` (writers with routing keys,
per-consumer reader groups, position-addressed readers, scope/stream admin)
— registered for streamingCluster ``type: pravega`` when the client binding
is importable, the same gating as kafka/pulsar.

Cluster configuration (reference keys, ``PravegaClientUtils.java:37-57``)::

    streamingCluster:
      type: pravega
      configuration:
        client:
          controller-uri: "tcp://localhost:9090"
          scope: "langstream"

Event encoding: Pravega events are opaque byte payloads with no headers, so
one JSON envelope carries the whole record (``value``/``key``/``headers``
with kind tags; raw bytes base64) — the same role the reference's
ObjectMapper serialization plays. Delivery semantics: the binding hands out
segment *slices*; a reader that dies before releasing a slice gets its
events redelivered to the group — at-least-once at slice granularity, which
the contiguity tracker upstream already tolerates (duplicates allowed,
loss not).
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import uuid
from typing import Any

from langstream_tpu.api.record import Record, SimpleRecord, now_millis
from langstream_tpu.api.topics import (
    OFFSET_HEADER,
    TopicAdmin,
    TopicConnectionsRuntime,
    TopicConsumer,
    TopicOffset,
    TopicProducer,
    TopicReader,
)

logger = logging.getLogger(__name__)


def _pravega():
    import pravega_client

    return pravega_client


def _cluster_config(configuration: dict[str, Any]) -> dict[str, Any]:
    cfg = configuration.get("configuration", configuration) or {}
    client = cfg.get("client", cfg)
    return {
        "controller_uri": client.get("controller-uri", "tcp://localhost:9090"),
        "scope": client.get("scope", "langstream"),
    }


def record_to_event(record: Record) -> tuple[bytes, str | None]:
    """→ (event payload bytes, routing key)."""

    def enc(value: Any) -> Any:
        if isinstance(value, bytes):
            return {"__b64__": base64.b64encode(value).decode("ascii")}
        return value

    envelope = {
        "value": enc(record.value),
        "key": enc(record.key),
        "headers": {
            k: enc(v) for k, v in record.headers if k != OFFSET_HEADER
        },
        "timestamp": record.timestamp,
    }
    routing_key = None
    if record.key is not None:
        routing_key = (
            record.key if isinstance(record.key, str) else json.dumps(record.key)
        )
    return json.dumps(envelope).encode("utf-8"), routing_key


def event_to_record(data: bytes, stream: str, position: Any) -> Record:
    def dec(value: Any) -> Any:
        if isinstance(value, dict) and set(value) == {"__b64__"}:
            return base64.b64decode(value["__b64__"])
        return value

    envelope = json.loads(data)
    headers = tuple(
        (k, dec(v)) for k, v in (envelope.get("headers") or {}).items()
    ) + ((OFFSET_HEADER, TopicOffset(stream, 0, str(position))),)
    return SimpleRecord(
        value=dec(envelope.get("value")),
        key=dec(envelope.get("key")),
        headers=headers,
        origin=stream,
        timestamp=envelope.get("timestamp") or now_millis(),
    )


class PravegaTopicConsumer(TopicConsumer):
    """One reader in a per-agent reader group (parity: the reference's
    ``reader-{uuid}`` groups). Slice events buffer locally; ``commit``
    releases fully-consumed slices back to the group."""

    def __init__(self, manager_factory, scope: str, stream: str, group: str,
                 track_pending: bool = True):
        self._manager_factory = manager_factory
        self.scope = scope
        self.stream = stream
        self.group = group
        # TopicReaders never commit, so tracking their pending events would
        # grow without bound and pin slices forever — they run untracked
        # (drained slices release immediately)
        self._track_pending = track_pending
        self._reader = None
        self._slice = None
        self._slice_future = None  # in-flight get_segment_slice, if any
        self._timed_out = False  # last empty read was a timeout, not a drain
        self._slices_received = 0  # slices the broker has handed out
        self._pending: dict[str, Any] = {}  # position → slice holding it
        self._counter = 0
        self._total_out = 0

    async def start(self) -> None:
        loop = asyncio.get_running_loop()

        def _open():
            manager = self._manager_factory()
            rg = manager.create_reader_group(self.group, self.scope, self.stream)
            return rg.create_reader(f"reader-{uuid.uuid4()}")

        self._reader = await loop.run_in_executor(None, _open)

    async def close(self) -> None:
        if self._slice_future is not None and not self._slice_future.done():
            # don't block shutdown on the blocked call, but don't abandon
            # its result either: release a late slice, swallow a late error
            reader = self._reader
            loop = asyncio.get_running_loop()

            def _dispose(fut) -> None:
                # runs on the event loop when the abandoned blocking call
                # finally resolves: route the (blocking) release back to an
                # executor thread, never run broker RPCs on the loop
                try:
                    late = fut.result()
                except Exception as e:
                    logger.debug("abandoned acquire resolved with error: %s", e)
                    return
                if late is not None and reader is not None:
                    def _release() -> None:
                        try:
                            reader.release_segment(late)
                        except Exception as e:
                            logger.debug(
                                "late segment release skipped "
                                "(reader already offline): %s", e,
                            )

                    try:
                        loop.run_in_executor(None, _release)
                    except RuntimeError:
                        pass  # loop already closed at shutdown

            self._slice_future.add_done_callback(_dispose)
            self._slice_future = None
        if self._reader is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, self._reader.reader_offline)
            self._reader = None

    def last_empty_was_timeout(self) -> bool:
        """True when the most recent empty ``read`` hit its timeout (nothing
        available) rather than a slice boundary (more may follow at once)."""
        return self._timed_out

    async def read(self, timeout: float | None = None) -> list[Record]:
        loop = asyncio.get_running_loop()
        # default every path to "not a timeout"; only the timeout return
        # flips it — new empty-return paths then fail safe (drain keeps
        # going on deadline rather than breaking early)
        self._timed_out = False
        if self._slice is None:
            # get_segment_slice blocks until the broker hands a slice out; a
            # bounded read must NOT abandon the blocked call (a second call
            # would double-consume), so the in-flight future is kept and
            # re-awaited on the next read
            if self._slice_future is None:
                # the bound method is captured on the loop thread: close()
                # nulls _reader, and the (possibly abandoned) blocking call
                # must not re-read the field mid-flight (RACE801)
                reader = self._reader
                self._slice_future = loop.run_in_executor(
                    None, reader.get_segment_slice
                )
            if timeout is not None:
                done, _ = await asyncio.wait(
                    {self._slice_future}, timeout=timeout
                )
                if not done:
                    self._timed_out = True
                    return []
            try:
                self._slice = await self._slice_future
                if self._slice is not None:
                    self._slices_received += 1
            finally:
                # a failed call is safe to retry (nothing was consumed);
                # clearing here keeps a transient broker error from wedging
                # every later read on the same cached exception
                self._slice_future = None
            if self._slice is None:
                return []
        # captured on the loop thread (same RACE801 discipline as _reader)
        current_slice = self._slice
        event = await loop.run_in_executor(
            None, lambda: next(iter(current_slice), None)
        )
        if event is None:
            # slice drained; release once everything it held is committed
            if not any(s is self._slice for s in self._pending.values()):
                await loop.run_in_executor(
                    None, self._reader.release_segment, self._slice
                )
            self._slice = None
            return []
        self._counter += 1
        position = f"{self.stream}:{self._counter}"
        record = event_to_record(event.data(), self.stream, position)
        if self._track_pending:
            self._pending[position] = self._slice
        self._total_out += 1
        return [record]

    async def commit(self, records: list[Record]) -> None:
        loop = asyncio.get_running_loop()
        for record in records:
            offset = record.header(OFFSET_HEADER)
            if offset is None:
                continue
            done_slice = self._pending.pop(str(offset.offset), None)
            # release a drained slice whose last pending event just committed
            if (
                done_slice is not None
                and done_slice is not self._slice
                and not any(s is done_slice for s in self._pending.values())
            ):
                await loop.run_in_executor(
                    None, self._reader.release_segment, done_slice
                )

    def total_out(self) -> int:
        return self._total_out


class PravegaTopicProducer(TopicProducer):
    def __init__(self, manager_factory, scope: str, stream: str):
        self._manager_factory = manager_factory
        self.scope = scope
        self.stream = stream
        self._writer = None
        self._total_in = 0

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._writer = await loop.run_in_executor(
            None,
            lambda: self._manager_factory().create_writer(self.scope, self.stream),
        )

    async def close(self) -> None:  # durable shutdown: flush buffered writes
        if self._writer is not None and hasattr(self._writer, "flush"):
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, self._writer.flush)
        self._writer = None

    async def write(self, record: Record) -> None:
        payload, routing_key = record_to_event(record)
        loop = asyncio.get_running_loop()
        # captured on the loop thread: close() nulls the field, and the
        # executor closure must not re-read it mid-flight (RACE801)
        writer = self._writer

        def _write():
            if routing_key is not None:
                result = writer.write_event_bytes(
                    payload, routing_key=routing_key
                )
            else:
                result = writer.write_event_bytes(payload)
            # the binding queues writes and returns a future; durability =
            # the broker acked, and the tracker upstream commits the source
            # offset when this returns — so block on the ack here
            if hasattr(result, "result"):
                result.result()

        await loop.run_in_executor(None, _write)
        self._total_in += 1

    def total_in(self) -> int:
        return self._total_in


class PravegaTopicReader(TopicReader):
    """Ephemeral reader group per reader (the reference does the same for
    gateway consumers, ``PravegaTopicConnectionsRuntimeProvider.java:112``).
    ``latest`` readers skip whatever is already in the stream."""

    def __init__(self, manager_factory, scope: str, stream: str, position: str):
        self._consumer = PravegaTopicConsumer(
            manager_factory, scope, stream, f"reader-{uuid.uuid4()}",
            track_pending=False,  # readers never commit
        )
        self.position = position

    async def start(self) -> None:
        await self._consumer.start()
        if self.position == "latest":
            # drain the backlog so only new events surface. Empty reads come
            # in two flavors the streak must distinguish: a SLICE-DRAIN
            # empty (more backlog may follow immediately) and a TIMEOUT
            # empty (nothing available right now). The drain ends on a
            # timeout *after data has flowed* (backlog consumed) — a slow
            # first slice delivery must not end it early, or history would
            # replay as live events. An entirely idle stream exits on the
            # deadline; under continuous writes the deadline also bounds
            # the wait ("latest" means roughly-now, not writers-paused).
            loop = asyncio.get_running_loop()
            deadline = loop.time() + 5.0
            while loop.time() < deadline:
                if await self._consumer.read(timeout=0.25):
                    continue
                if not self._consumer.last_empty_was_timeout():
                    continue  # slice boundary: more backlog may follow
                if self._consumer._slices_received > 0:
                    # the broker HAS delivered slices and now nothing more
                    # is immediately available: drained. Before any slice
                    # arrives, a timeout is ambiguous (slow backlog delivery
                    # vs idle stream) — correctness wins, so only the
                    # deadline ends that wait (history must never replay as
                    # live events; an idle stream pays the deadline once at
                    # connect).
                    break

    async def close(self) -> None:
        await self._consumer.close()

    async def read(self, timeout: float | None = None) -> list[Record]:
        return await self._consumer.read(
            timeout=timeout if timeout is not None else 0.5
        )


class PravegaTopicAdmin(TopicAdmin):
    def __init__(self, manager_factory, scope: str):
        self._manager_factory = manager_factory
        self.scope = scope

    async def create_topic(
        self, name: str, partitions: int = 1, config: dict[str, Any] | None = None
    ) -> None:
        loop = asyncio.get_running_loop()

        def _create():
            manager = self._manager_factory()
            manager.create_scope(self.scope)
            manager.create_stream(self.scope, name, max(1, partitions))

        await loop.run_in_executor(None, _create)

    async def delete_topic(self, name: str) -> None:
        loop = asyncio.get_running_loop()

        def _delete():
            manager = self._manager_factory()
            manager.seal_stream(self.scope, name)
            manager.delete_stream(self.scope, name)

        await loop.run_in_executor(None, _delete)


class PravegaTopicConnectionsRuntime(TopicConnectionsRuntime):
    def __init__(self) -> None:
        self._config: dict[str, Any] = {}
        self._manager = None

    def init(self, streaming_cluster_configuration: dict[str, Any]) -> None:
        self._config = _cluster_config(streaming_cluster_configuration)

    def _manager_factory(self):
        if self._manager is None:
            self._manager = _pravega().StreamManager(
                self._config["controller_uri"]
            )
        return self._manager

    def create_consumer(
        self, agent_id: str, config: dict[str, Any]
    ) -> TopicConsumer:
        group = config.get("group") or f"langstream-{agent_id}"
        return PravegaTopicConsumer(
            self._manager_factory, self._config["scope"], config["topic"], group
        )

    def create_producer(
        self, agent_id: str, config: dict[str, Any]
    ) -> TopicProducer:
        return PravegaTopicProducer(
            self._manager_factory, self._config["scope"], config["topic"]
        )

    def create_reader(
        self,
        config: dict[str, Any],
        initial_position: str = "latest",
    ) -> TopicReader:
        return PravegaTopicReader(
            self._manager_factory, self._config["scope"], config["topic"],
            initial_position,
        )

    def create_topic_admin(self) -> TopicAdmin:
        return PravegaTopicAdmin(self._manager_factory, self._config["scope"])

    async def close(self) -> None:
        self._manager = None
