"""Pulsar streaming runtime (gated on the ``pulsar`` client library).

Parity: ``langstream-pulsar-runtime`` —
``PulsarTopicConnectionsRuntimeProvider.java`` (shared-subscription
consumers with per-message acks, producers with serializer inference,
position-addressed readers for the gateway, admin topic create/delete) —
registered for streamingCluster ``type: pulsar`` when the client library is
importable (``langstream_tpu/runtime/__init__.py``), exactly like the kafka
runtime gates on ``confluent_kafka``.

Pulsar semantics vs Kafka: acknowledgement is per *message id*, not a
contiguous offset prefix — so there is no offset tracker here; the consumer
holds unacked message handles and acks them individually on commit
(redelivery of unacked messages after reconnect is the broker's job).
Topic auto-creation is a Pulsar broker default, so the admin only calls the
REST API when an ``admin-url`` is configured.

Cluster configuration (both the reference's pulsar instance shape and flat
keys are accepted)::

    streamingCluster:
      type: pulsar
      configuration:
        service-url: "pulsar://localhost:6650"
        admin-url: "http://localhost:8080"     # optional (topic admin REST)
        tenant: "public"
        namespace: "default"

The wire encoding mirrors the kafka runtime (shared helpers): values/keys
pick an encoding from the Python type; Pulsar *properties* are strings, so
header payloads travel as UTF-8 with a ``__ls_kinds`` JSON property naming
any non-string kinds.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import Any

from langstream_tpu.api.record import Record, SimpleRecord, now_millis
from langstream_tpu.api.topics import (
    OFFSET_HEADER,
    TopicAdmin,
    TopicConnectionsRuntime,
    TopicConsumer,
    TopicOffset,
    TopicProducer,
    TopicReader,
)
from langstream_tpu.runtime.kafka_broker import (
    deserialize_datum,
    serialize_datum_kind,
)

logger = logging.getLogger(__name__)

KINDS_PROP = "__ls_kinds"


def _pulsar():
    import pulsar

    return pulsar


def _cluster_config(configuration: dict[str, Any]) -> dict[str, Any]:
    cfg = configuration.get("configuration", configuration) or {}
    return {
        "service_url": cfg.get("service-url")
        or cfg.get("serviceUrl")
        or cfg.get("brokerServiceUrl")
        or "pulsar://localhost:6650",
        "admin_url": cfg.get("admin-url") or cfg.get("webServiceUrl"),
        "tenant": cfg.get("tenant", "public"),
        "namespace": cfg.get("namespace", "default"),
    }


def _to_property(value: Any) -> tuple[str, str | None]:
    """Encode one header/key value into a Pulsar string property + kind.
    Bytes travel base64 (properties are strings; lossy UTF-8 decoding would
    corrupt binary header values the kafka runtime preserves exactly)."""
    if value is None:
        return "", "null"
    if isinstance(value, bytes):
        return base64.b64encode(value).decode("ascii"), "b64"
    data, kind = serialize_datum_kind(value)
    return (data or b"").decode("utf-8"), kind


def _from_property(raw: str, kind: str | None) -> Any:
    if kind == "null":
        return None
    if kind == "b64":
        return base64.b64decode(raw)
    return deserialize_datum(raw.encode("utf-8"), kind)


def record_to_payload(record: Record) -> tuple[bytes, dict[str, str], str | None]:
    """→ (payload bytes, properties, partition key)."""
    data, value_kind = serialize_datum_kind(record.value)
    kinds: dict[str, str] = {}
    if value_kind:
        kinds["__value"] = value_kind
    properties: dict[str, str] = {}
    for k, v in record.headers:
        if k == OFFSET_HEADER:
            continue  # transport-local
        properties[k], hkind = _to_property(v)
        if hkind:
            kinds[k] = hkind
    partition_key: str | None = None
    if record.key is not None:
        partition_key, kkind = _to_property(record.key)
        if kkind:
            kinds["__key"] = kkind
    if kinds:
        properties[KINDS_PROP] = json.dumps(kinds)
    return data or b"", properties, partition_key


def message_to_record(msg: Any, topic: str) -> Record:
    properties = dict(msg.properties() or {})
    kinds: dict[str, str] = {}
    raw_kinds = properties.pop(KINDS_PROP, None)
    if raw_kinds:
        try:
            kinds = json.loads(raw_kinds)
        except json.JSONDecodeError:
            pass
    headers = tuple(
        (k, _from_property(v, kinds.get(k))) for k, v in properties.items()
    ) + ((OFFSET_HEADER, TopicOffset(topic, 0, str(msg.message_id()))),)
    partition_key = msg.partition_key() if hasattr(msg, "partition_key") else None
    key = (
        _from_property(partition_key, kinds.get("__key"))
        if partition_key
        else None
    )
    ts = None
    if hasattr(msg, "publish_timestamp"):
        ts = msg.publish_timestamp() or None
    return SimpleRecord(
        value=deserialize_datum(msg.data(), kinds.get("__value")),
        key=key,
        headers=headers,
        origin=topic,
        timestamp=ts if ts else now_millis(),
    )


class PulsarTopicConsumer(TopicConsumer):
    """Shared-subscription consumer; blocking client calls run on the
    default executor. Unacked message handles are kept by message-id string
    so ``commit`` acks exactly the records the runner processed."""

    def __init__(self, client_factory, topic: str, subscription: str):
        self._client_factory = client_factory
        self.topic = topic
        self.subscription = subscription
        self._consumer = None
        self._unacked: dict[str, Any] = {}
        self._total_out = 0

    async def start(self) -> None:
        pulsar = _pulsar()
        loop = asyncio.get_running_loop()
        client = self._client_factory()

        def _subscribe():
            return client.subscribe(
                self.topic,
                subscription_name=self.subscription,
                consumer_type=pulsar.ConsumerType.Shared,
                negative_ack_redelivery_delay_ms=1000,
            )

        self._consumer = await loop.run_in_executor(None, _subscribe)

    async def close(self) -> None:
        if self._consumer is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, self._consumer.close)
            self._consumer = None

    async def read(self) -> list[Record]:
        pulsar = _pulsar()
        loop = asyncio.get_running_loop()
        # captured on the loop thread: close() nulls the field, and the
        # executor closure must not re-read it mid-flight (RACE801)
        consumer = self._consumer

        def _receive():
            try:
                return consumer.receive(timeout_millis=500)
            except pulsar.Timeout:
                return None
            except Exception as e:  # pulsar maps timeouts to generic errors
                if "imeout" in str(e):
                    return None
                raise

        msg = await loop.run_in_executor(None, _receive)
        if msg is None:
            return []
        record = message_to_record(msg, self.topic)
        offset = record.header(OFFSET_HEADER)
        self._unacked[str(offset.offset)] = msg
        self._total_out += 1
        return [record]

    async def commit(self, records: list[Record]) -> None:
        loop = asyncio.get_running_loop()
        for record in records:
            offset = record.header(OFFSET_HEADER)
            if offset is None:
                continue
            msg = self._unacked.pop(str(offset.offset), None)
            if msg is not None:
                await loop.run_in_executor(
                    None, self._consumer.acknowledge, msg
                )

    def total_out(self) -> int:
        return self._total_out


class PulsarTopicProducer(TopicProducer):
    def __init__(self, client_factory, topic: str):
        self._client_factory = client_factory
        self.topic = topic
        self._producer = None
        self._total_in = 0

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        client = self._client_factory()
        self._producer = await loop.run_in_executor(
            None, lambda: client.create_producer(self.topic)
        )

    async def close(self) -> None:
        if self._producer is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, self._producer.close)
            self._producer = None

    async def write(self, record: Record) -> None:
        payload, properties, partition_key = record_to_payload(record)
        loop = asyncio.get_running_loop()
        # captured on the loop thread — see PulsarTopicConsumer.read
        producer = self._producer

        def _send():
            kwargs: dict[str, Any] = {"properties": properties}
            if partition_key is not None:
                kwargs["partition_key"] = partition_key
            producer.send(payload, **kwargs)

        await loop.run_in_executor(None, _send)
        self._total_in += 1

    def total_in(self) -> int:
        return self._total_in


class PulsarTopicReader(TopicReader):
    """Position-addressed reader (gateway consume side)."""

    def __init__(self, client_factory, topic: str, position: str):
        self._client_factory = client_factory
        self.topic = topic
        self.position = position
        self._reader = None

    async def start(self) -> None:
        pulsar = _pulsar()
        loop = asyncio.get_running_loop()
        client = self._client_factory()
        start = (
            pulsar.MessageId.earliest
            if self.position == "earliest"
            else pulsar.MessageId.latest
        )
        self._reader = await loop.run_in_executor(
            None, lambda: client.create_reader(self.topic, start)
        )

    async def close(self) -> None:
        if self._reader is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, self._reader.close)
            self._reader = None

    async def read(self, timeout: float | None = None) -> list[Record]:
        pulsar = _pulsar()
        loop = asyncio.get_running_loop()
        millis = int((timeout if timeout is not None else 0.5) * 1000)
        # captured on the loop thread — see PulsarTopicConsumer.read
        reader = self._reader

        def _read():
            try:
                return reader.read_next(timeout_millis=millis)
            except pulsar.Timeout:
                return None
            except Exception as e:
                if "imeout" in str(e):
                    return None
                raise

        msg = await loop.run_in_executor(None, _read)
        return [message_to_record(msg, self.topic)] if msg is not None else []


class PulsarTopicAdmin(TopicAdmin):
    """Admin REST calls when ``admin-url`` is configured; otherwise a no-op
    (Pulsar brokers auto-create topics by default)."""

    def __init__(self, admin_url: str | None, tenant: str, namespace: str):
        self.admin_url = admin_url.rstrip("/") if admin_url else None
        self.tenant = tenant
        self.namespace = namespace

    def _topic_path(self, name: str) -> str:
        if "/" in name:  # already tenant/ns/topic
            return name
        return f"{self.tenant}/{self.namespace}/{name}"

    async def create_topic(
        self, name: str, partitions: int = 1, config: dict[str, Any] | None = None
    ) -> None:
        if not self.admin_url:
            logger.debug("no admin-url; relying on broker topic auto-create")
            return
        import aiohttp

        path = f"/admin/v2/persistent/{self._topic_path(name)}"
        async with aiohttp.ClientSession() as session:
            if partitions > 1:
                url = f"{self.admin_url}{path}/partitions"
                async with session.put(url, json=partitions) as resp:
                    if resp.status not in (200, 204, 409):
                        raise RuntimeError(
                            f"pulsar admin create {name}: {resp.status} "
                            f"{await resp.text()}"
                        )
            else:
                async with session.put(f"{self.admin_url}{path}") as resp:
                    if resp.status not in (200, 204, 409):
                        raise RuntimeError(
                            f"pulsar admin create {name}: {resp.status} "
                            f"{await resp.text()}"
                        )

    async def delete_topic(self, name: str) -> None:
        if not self.admin_url:
            return
        import aiohttp

        path = f"/admin/v2/persistent/{self._topic_path(name)}"
        async with aiohttp.ClientSession() as session:
            async with session.delete(
                f"{self.admin_url}{path}?force=true"
            ) as resp:
                if resp.status not in (200, 204, 404):
                    raise RuntimeError(
                        f"pulsar admin delete {name}: {resp.status} "
                        f"{await resp.text()}"
                    )


class PulsarTopicConnectionsRuntime(TopicConnectionsRuntime):
    """One shared ``pulsar.Client`` per runtime instance."""

    def __init__(self) -> None:
        self._config: dict[str, Any] = {}
        self._client = None

    def init(self, streaming_cluster_configuration: dict[str, Any]) -> None:
        self._config = _cluster_config(streaming_cluster_configuration)

    def _client_factory(self):
        if self._client is None:
            pulsar = _pulsar()
            self._client = pulsar.Client(self._config["service_url"])
        return self._client

    def create_consumer(
        self, agent_id: str, config: dict[str, Any]
    ) -> TopicConsumer:
        subscription = (
            config.get("subscription")
            or config.get("group")
            or f"langstream-{agent_id}"
        )
        return PulsarTopicConsumer(
            self._client_factory, config["topic"], subscription
        )

    def create_producer(
        self, agent_id: str, config: dict[str, Any]
    ) -> TopicProducer:
        return PulsarTopicProducer(self._client_factory, config["topic"])

    def create_reader(
        self,
        config: dict[str, Any],
        initial_position: str = "latest",
    ) -> TopicReader:
        return PulsarTopicReader(
            self._client_factory, config["topic"], initial_position
        )

    def create_topic_admin(self) -> TopicAdmin:
        return PulsarTopicAdmin(
            self._config.get("admin_url"),
            self._config.get("tenant", "public"),
            self._config.get("namespace", "default"),
        )

    async def close(self) -> None:
        if self._client is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, self._client.close)
            self._client = None
