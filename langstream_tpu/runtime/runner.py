"""The agent runner: one replica's hot loop.

Parity: ``AgentRunner`` (``langstream-runtime-impl/.../agent/AgentRunner.java``)
— wiring (``:138``): resolve the streaming runtime, build
consumer/producer/dead-letter, wrap defaults ``TopicConsumerSource`` /
``TopicProducerSink`` (``:338,354``); hot loop (``runMainLoop``, ``:651-730``):
``source.read() → processor.process(records, sink) → write results``, with the
:class:`~langstream_tpu.runtime.tracker.SourceRecordTracker` committing source
offsets only after all derived writes land, retry/skip/dead-letter per
``ErrorsSpec``, and graceful drain on shutdown (``:562``).

The loop is a single asyncio task; processors may resolve results out of
order (the GPU/TPU-serving agents do), commit contiguity is preserved by the
consumer.
"""

from __future__ import annotations

import asyncio
import logging
import os
from pathlib import Path
from typing import Any

from langstream_tpu.api.agent import (
    AgentCode,
    AgentContext,
    AgentProcessor,
    AgentService,
    AgentSink,
    AgentSource,
    ComponentType,
    RecordSink,
    SourceRecordAndResult,
)
from langstream_tpu.api.application import ErrorsSpec
from langstream_tpu.api.execution_plan import AgentNode, ExecutionPlan
from langstream_tpu.api.metrics import PrometheusMetricsReporter
from langstream_tpu.api.record import Record, SimpleRecord
from langstream_tpu.api.registry import AgentCodeRegistry
from langstream_tpu.api.topics import (
    TopicConnectionsRuntime,
    TopicConsumer,
    TopicProducer,
)
from langstream_tpu.core.asyncutil import spawn_retained
from langstream_tpu.core.tracing import TRACE_HEADER, TraceContext, start_span
from langstream_tpu.gateway.router import (
    BOUNCE_HEADER,
    MAX_BOUNCES,
    REPLICA_HEADER,
    split_replica_target,
)
from langstream_tpu.runtime.composite import CompositeAgentProcessor
from langstream_tpu.runtime.errors_handler import (
    FailureAction,
    StandardErrorsHandler,
    deadletter_record,
)
from langstream_tpu.runtime.tracker import SourceRecordTracker

log = logging.getLogger(__name__)

DESTINATION_TOPIC_HEADER = "langstream-destination-topic"


class TopicConsumerSource(AgentSource):
    """Default source: reads the node's input topic
    (parity: ``AgentRunner.java:338``)."""

    def __init__(self, consumer: TopicConsumer):
        self.consumer = consumer

    async def start(self) -> None:
        await self.consumer.start()

    async def close(self) -> None:
        await self.consumer.close()

    async def read(self) -> list[Record]:
        return await self.consumer.read()

    async def commit(self, records: list[Record]) -> None:
        await self.consumer.commit(records)


class TopicProducerSink(AgentSink):
    """Default sink: writes to the node's output topic, honoring per-record
    destination-topic routing (used by the ``dispatch`` agent)."""

    def __init__(
        self,
        producer: TopicProducer | None,
        runtime: TopicConnectionsRuntime,
        agent_id: str,
    ):
        self.producer = producer
        self.runtime = runtime
        self.agent_id = agent_id
        self._extra_producers: dict[str, TopicProducer] = {}

    async def start(self) -> None:
        if self.producer:
            await self.producer.start()

    async def close(self) -> None:
        if self.producer:
            await self.producer.close()
        for p in self._extra_producers.values():
            await p.close()

    async def write(self, record: Record) -> None:
        destination = record.header(DESTINATION_TOPIC_HEADER)
        if destination:
            # strip the routing header so downstream nodes fall back to their
            # own configured outputs instead of re-routing forever
            routed = SimpleRecord(
                value=record.value,
                key=record.key,
                headers=tuple(
                    (k, v)
                    for k, v in record.headers
                    if k != DESTINATION_TOPIC_HEADER
                ),
                origin=record.origin,
                timestamp=record.timestamp,
            )
            producer = await self._producer_for(destination)
            await producer.write(routed)
            return
        if self.producer is None:
            # terminal agent without output: drop (the reference logs these)
            return
        await self.producer.write(record)

    async def _producer_for(self, topic: str) -> TopicProducer:
        if topic not in self._extra_producers:
            producer = self.runtime.create_producer(self.agent_id, {"topic": topic})
            await producer.start()
            self._extra_producers[topic] = producer
        return self._extra_producers[topic]


class _PassthroughProcessor(AgentProcessor):
    def process(self, records: list[Record], sink: RecordSink) -> None:
        for r in records:
            sink.emit(SourceRecordAndResult(r, [r], None))


class _RunnerRecordSink:
    """The RecordSink handed to the processor: applies the error policy and
    drives the write side + tracker."""

    def __init__(self, runner: "AgentRunner"):
        self.runner = runner
        self._tasks: set = set()

    def emit(self, result: SourceRecordAndResult) -> None:
        # a failed _handle_result must not vanish with its record un-acked
        spawn_retained(
            self.runner._handle_result(result),
            self._tasks,
            log,
            "result handling failed",
        )

    def emit_error(self, source_record: Record, error: Exception) -> None:
        self.emit(SourceRecordAndResult(source_record, [], error))


class AgentRunner:
    """Runs one replica of one (possibly composite) agent node."""

    def __init__(
        self,
        plan: ExecutionPlan,
        node: AgentNode,
        replica: int = 0,
        state_dir: Path | None = None,
    ):
        self.plan = plan
        self.node = node
        self.replica = replica
        self.state_dir = state_dir
        self.agent_id = f"{plan.application_id}-{node.id}"
        self._running = False
        self._stop_requested = asyncio.Event()
        self._fatal: Exception | None = None
        self.records_in = 0
        self.records_out = 0
        self.errors_total = 0
        # backpressure: max records read-but-not-terminal before the loop
        # stops polling (parity: the reference loop awaits processing; we
        # allow a bounded pipeline depth instead so TPU batches can fill)
        self.max_pending = int(
            (node.configuration or {}).get("max-pending-records", 512)
        )
        self._inflight = 0
        self._loop_task: asyncio.Task | None = None
        self._service_task: asyncio.Task | None = None
        # replica routing (gateway/router.py): the gateway stamps a
        # `langstream-replica` target; this consumer honors stamps whose
        # base names ITS StatefulSet (in-cluster the pod name carries
        # both base and ordinal; dev/test mode falls back to the
        # replica index) and bounces mismatches back to the input topic
        pod_name = os.environ.get("LS_POD_NAME")
        if pod_name:
            base, ordinal = split_replica_target(pod_name)
            self._routing_base = base
            self._routing_ordinal = (
                ordinal if ordinal is not None else replica
            )
        else:
            self._routing_base = ""
            self._routing_ordinal = replica
        self._reroute_producer: TopicProducer | None = None
        self.records_rerouted = 0
        # per-record trace spans, opened at read and closed when the record
        # reaches a terminal state (written / committed / dead-lettered);
        # keyed by id() like the tracker (record values may be dicts)
        self._record_spans: dict[int, Any] = {}

    # ---- wiring ----------------------------------------------------------

    async def start(self) -> None:
        streaming = self.plan.application.instance.streaming_cluster
        from langstream_tpu.api.topics import TopicConnectionsRuntimeRegistry

        self.topics_runtime = TopicConnectionsRuntimeRegistry.get_runtime(
            {"type": streaming.type, "configuration": streaming.configuration}
        )

        node = self.node
        consumer: TopicConsumer | None = None
        producer: TopicProducer | None = None
        self.deadletter_producer: TopicProducer | None = None

        if node.input is not None:
            consumer = self.topics_runtime.create_consumer(
                self.agent_id,
                {
                    "topic": node.input.topic,
                    "group": self.agent_id,
                    # replica identity: runtimes with static partition
                    # assignment (wire kafka) split partitions on these;
                    # group-rebalance runtimes ignore them
                    "replica-index": self.replica,
                    "num-replicas": max(1, node.resources.parallelism),
                },
            )
            if node.input.deadletter_enabled:
                self.deadletter_producer = (
                    self.topics_runtime.create_deadletter_producer(
                        self.agent_id, {"topic": node.input.topic}
                    )
                )
        if node.output is not None:
            producer = self.topics_runtime.create_producer(
                self.agent_id, {"topic": node.output.topic}
            )

        # agent instantiation (composite → chain of processors)
        agents = [
            await self._instantiate(cfg.type, cfg.configuration, cfg.id)
            for cfg in node.agents
        ]

        self.source: AgentSource
        self.sink: AgentSink
        self.service: AgentService | None = None
        processors: list[AgentProcessor] = []

        first, last = agents[0], agents[-1]
        if isinstance(first, AgentService):
            self.service = first
            self.source = _NullSource()
            self.sink = TopicProducerSink(None, self.topics_runtime, self.agent_id)
            self.processor = _PassthroughProcessor()
        else:
            if isinstance(first, AgentSource):
                self.source = first
                middles = agents[1:]
            else:
                if consumer is None:
                    raise RuntimeError(
                        f"agent {node.id} is not a source and has no input topic"
                    )
                self.source = TopicConsumerSource(consumer)
                middles = agents
            if middles and isinstance(middles[-1], AgentSink):
                self.sink = middles[-1]
                middles = middles[:-1]
            else:
                self.sink = TopicProducerSink(
                    producer, self.topics_runtime, self.agent_id
                )
            for a in middles:
                if not isinstance(a, AgentProcessor):
                    raise RuntimeError(
                        f"agent {a.agent_type!r} cannot sit mid-pipeline "
                        f"(component type {a.component_type().value})"
                    )
                processors.append(a)
            self.processor = (
                processors[0]
                if len(processors) == 1
                else CompositeAgentProcessor(processors)
                if processors
                else _PassthroughProcessor()
            )

        # context + lifecycle
        metrics = PrometheusMetricsReporter(agent_id=self.agent_id)
        # runtime counters on /metrics (parity: the reference's per-agent
        # Prometheus counters; scraped by deploy/metrics/prometheus.yml)
        self._m_records_in = metrics.counter(
            "records_in", "records read from the source"
        )
        self._m_records_out = metrics.counter(
            "records_out", "records written to the sink"
        )
        self._m_errors = metrics.counter("record_errors", "record failures")
        self._m_pending = metrics.gauge("records_pending", "in-flight records")
        self._m_latency = metrics.histogram(
            "record_process_seconds",
            "per-record latency from source read to terminal write/commit",
        )
        context = AgentContext(
            agent_id=self.node.id,
            global_agent_id=self.agent_id,
            persistent_state_dir=(
                self.state_dir / f"{self.node.id}-{self.replica}"
                if self.state_dir
                else None
            ),
            metrics=metrics,
            topic_producer_factory=self._make_producer,
            critical_failure_handler=self._on_critical_failure,
        )
        self.context = context
        self.tracker = SourceRecordTracker(self.source.commit)
        self.errors_handler = StandardErrorsHandler(self.node.errors or ErrorsSpec())
        self.record_sink = _RunnerRecordSink(self)

        # note: a CompositeAgentProcessor propagates setup/start/close to its
        # children, so only the top-level trio is driven here.
        for a in dict.fromkeys(
            [self.source, self.processor, self.sink]
            + ([self.service] if self.service else [])
        ):
            await a.setup(context)
        await self.source.start()
        await self.sink.start()
        await self.processor.start()
        if self.deadletter_producer:
            await self.deadletter_producer.start()
        if self.service:
            await self.service.start()
            self._service_task = asyncio.ensure_future(self.service.run())

        self._running = True
        self._loop_task = asyncio.ensure_future(self._main_loop())

    async def _instantiate(self, agent_type: str, configuration: dict[str, Any], agent_id: str) -> AgentCode:
        agent = AgentCodeRegistry.get_agent_code(agent_type)
        agent.agent_id = agent_id
        cfg = dict(configuration)
        # ambient application context for agents that reference shared
        # resources (model providers, datasources) or globals
        cfg["__resources__"] = {
            rid: {"type": r.type, "name": r.name, **r.configuration}
            for rid, r in self.plan.application.resources.items()
        }
        cfg["__globals__"] = self.plan.application.instance.globals_
        cfg["__application_id__"] = self.plan.application_id
        if self.plan.application.directory:
            # custom python/sidecar agents resolve their code relative to
            # the application package (its python/ dir)
            cfg.setdefault(
                "__application_directory__", self.plan.application.directory
            )
        await agent.init(cfg)
        return agent

    def _make_producer(self, topic: str):
        producer = self.topics_runtime.create_producer(self.agent_id, {"topic": topic})

        class _Handle:
            def __init__(self, producer: TopicProducer):
                self._producer = producer
                self._started = False

            async def write(self, record: Record) -> None:
                if not self._started:
                    await self._producer.start()
                    self._started = True
                await self._producer.write(record)

        return _Handle(producer)

    def _on_critical_failure(self, error: Exception) -> None:
        log.error("agent %s critical failure: %s", self.agent_id, error)
        self._fatal = error
        self._stop_requested.set()

    # ---- hot loop --------------------------------------------------------

    async def _main_loop(self) -> None:
        try:
            while not self._stop_requested.is_set():
                while (
                    self._inflight >= self.max_pending
                    and not self._stop_requested.is_set()
                ):
                    await asyncio.sleep(0.002)
                records = await self.source.read()
                if self._stop_requested.is_set():
                    break
                if records and self.node.input is not None:
                    records = await self._honor_replica_routing(records)
                if not records:
                    await asyncio.sleep(0)
                    continue
                self.records_in += len(records)
                self._m_records_in(len(records))
                self._inflight += len(records)
                self._m_pending(self._inflight)
                records = [self._begin_record_trace(r) for r in records]
                self.processor.process(records, self.record_sink)
                await asyncio.sleep(0)
        except Exception as e:  # loop-level failure is fatal for the replica
            self._fatal = e
            log.exception("agent %s main loop failed", self.agent_id)

    async def _honor_replica_routing(self, records: list[Record]) -> list[Record]:
        """Filter one read batch against `langstream-replica` stamps
        (docs/FLEET.md): records addressed to THIS replica (or to no one,
        or to a different agent's pods) pass through; records addressed
        to a sibling replica of this StatefulSet re-produce back onto
        the input topic and commit here, so consumer-group partition
        spread and the gateway's routing intent converge. Bounces are
        capped: once a record has hopped ``MAX_BOUNCES`` times its
        target is evidently gone (scaled away mid-flight) and serving it
        on the wrong replica — a cold prefix cache, nothing worse —
        beats letting it orbit the topic."""
        kept: list[Record] = []
        for record in records:
            target = record.header(REPLICA_HEADER)
            if not target:
                kept.append(record)
                continue
            base, ordinal = split_replica_target(str(target))
            addressed_here = ordinal is not None and (
                base == "" or base == self._routing_base
            )
            if not addressed_here or ordinal == self._routing_ordinal:
                kept.append(record)
                continue
            if record.key is not None:
                # keyed records hash back to the SAME partition — this
                # consumer — so a bounce is two broker writes that land
                # the record right back here; serving it locally is the
                # only move that terminates
                kept.append(record)
                continue
            try:
                # the bounce header rides client-suppliable gateway
                # payloads: garbage reads as over the cap, never as a
                # loop-killing ValueError
                bounces = int(record.header(BOUNCE_HEADER) or 0)
            except (TypeError, ValueError):
                bounces = MAX_BOUNCES
            if bounces >= MAX_BOUNCES:
                kept.append(record)
                continue
            if not await self._reroute(record, bounces + 1):
                kept.append(record)
        return kept

    async def _reroute(self, record: Record, bounces: int) -> bool:
        try:
            producer = self._reroute_producer
            if producer is None:
                producer = self.topics_runtime.create_producer(
                    f"{self.agent_id}-reroute",
                    {"topic": self.node.input.topic},
                )
                await producer.start()
                self._reroute_producer = producer
            await producer.write(
                record.with_headers({BOUNCE_HEADER: str(bounces)})
            )
        except Exception:
            # a transient broker failure must not kill the main loop the
            # way a processing error never would: serve the record here
            # (cold prefix cache, nothing worse) and rebuild the producer
            # on the next bounce
            log.exception(
                "agent %s reroute produce failed; serving locally",
                self.agent_id,
            )
            dead, self._reroute_producer = self._reroute_producer, None
            if dead is not None:
                try:
                    await dead.close()
                except Exception as close_err:
                    log.debug(
                        "closing broken reroute producer failed: %s",
                        close_err,
                    )
            return False
        self.records_rerouted += 1
        # journey ledger (serving/journey.py): a replica bounce is a
        # lifecycle edge an operator must be able to SEE when a request's
        # TTFT decomposes — keyed by the record's trace id, like every
        # other edge of the journey
        ctx = TraceContext.parse(record.header(TRACE_HEADER))
        if ctx is not None:
            from langstream_tpu.serving.journey import JOURNEYS

            JOURNEYS.record(
                ctx.trace_id, "bounce",
                agent=self.agent_id, replica=self.replica, bounces=bounces,
            )
        # the re-produced copy is this record's continuation: commit the
        # original (zero local results) so the source offset advances
        self.tracker.track(record, 0)
        await self.tracker.commit_if_tracked_empty(record)
        return True

    def _begin_record_trace(self, record: Record) -> Record:
        """Open the per-record hop span and stamp its context into the
        record's ``langstream-trace`` header (creating a root trace when the
        record arrived without one), so composite stages, the serving
        engine, and every downstream hop parent under this one."""
        ctx = TraceContext.parse(record.header(TRACE_HEADER))
        span = start_span(
            "agent.process",
            service=self.agent_id,
            parent=ctx,
            attributes={"agent": self.node.id, "replica": self.replica},
        )
        record = record.with_headers({TRACE_HEADER: span.context().to_header()})
        self._record_spans[id(record)] = span
        return record

    def _finish_record_trace(
        self, record: Record, error: Exception | None = None, **attributes: Any
    ) -> None:
        span = self._record_spans.pop(id(record), None)
        if span is None:
            return
        for key, value in attributes.items():
            span.set_attribute(key, value)
        self._m_latency(span.end(error=error))

    async def _handle_result(self, result: SourceRecordAndResult) -> None:
        if result.error is not None:
            await self._handle_error(result.source_record, result.error)
            return
        self.errors_handler.clear(result.source_record)
        self._inflight = max(0, self._inflight - 1)
        self._m_pending(self._inflight)
        self.tracker.track(result.source_record, len(result.results))
        if not result.results:
            await self.tracker.commit_if_tracked_empty(result.source_record)
            self._finish_record_trace(result.source_record, results=0)
            return
        src_trace = result.source_record.header(TRACE_HEADER)
        for record in result.results:
            if src_trace is not None and record.header(TRACE_HEADER) is None:
                # processors that rebuild records from scratch must not
                # break the trace chain mid-pipeline
                record = record.with_headers({TRACE_HEADER: src_trace})
            try:
                await self.sink.write(record)
                self.records_out += 1
                self._m_records_out(1)
                await self.tracker.record_written(result.source_record)
            except Exception as e:
                await self.tracker.record_failed(result.source_record)
                self._inflight += 1  # re-enters error handling below
                await self._handle_error(result.source_record, e)
                return
        self._finish_record_trace(
            result.source_record, results=len(result.results)
        )

    async def _handle_error(self, source_record: Record, error: Exception) -> None:
        self.errors_total += 1
        self._m_errors(1)
        action = self.errors_handler.handle(source_record, error)
        if action == FailureAction.RETRY:
            # single-record retry, documented out-of-order; stays in flight
            # (and its span stays open — retries are one logical attempt)
            span = self._record_spans.get(id(source_record))
            if span is not None:
                span.set_attribute(
                    "retries", int(span.attributes.get("retries", 0)) + 1
                )
            self.processor.process([source_record], self.record_sink)
            return
        self._inflight = max(0, self._inflight - 1)
        self._m_pending(self._inflight)
        self._finish_record_trace(
            source_record, error=error, outcome=action.value
        )
        if action == FailureAction.SKIP:
            await self.tracker.commit_now(source_record)
        elif action == FailureAction.DEAD_LETTER:
            if self.deadletter_producer is not None:
                await self.deadletter_producer.write(
                    deadletter_record(source_record, error)
                )
            await self.tracker.commit_now(source_record)
        else:  # FAIL
            if isinstance(self.source, AgentSource):
                try:
                    await self.source.permanent_failure(source_record, error)
                except Exception as e:
                    self._fatal = e
            self._stop_requested.set()

    # ---- lifecycle -------------------------------------------------------

    async def stop(self, drain_timeout: float = 10.0) -> None:
        self._stop_requested.set()
        if self._loop_task is not None:
            await self._loop_task
        await self.tracker.wait_for_no_pending(drain_timeout)
        if self._service_task is not None:
            self._service_task.cancel()
            try:
                await self._service_task
            except asyncio.CancelledError:
                pass
            except Exception as e:
                log.debug("service task errored at stop: %s", e)
        for closer in (self.processor, self.sink, self.source):
            try:
                await closer.close()
            except Exception:
                log.exception("error closing %s", closer)
        if self.deadletter_producer:
            await self.deadletter_producer.close()
        if self._reroute_producer is not None:
            await self._reroute_producer.close()
        await self.topics_runtime.close()
        self._running = False
        if self._fatal is not None:
            raise self._fatal

    def info(self) -> dict[str, Any]:
        return {
            "agent-id": self.agent_id,
            "type": self.node.agent_type,
            "component-type": self.node.component_type,
            "replica": self.replica,
            "records-in": self.records_in,
            "records-out": self.records_out,
            "records-rerouted": self.records_rerouted,
            "errors": self.errors_total,
            "pending": self.tracker.pending_count() if hasattr(self, "tracker") else 0,
            "agent-info": self.processor.agent_info() if hasattr(self, "processor") else {},
        }


class _NullSource(AgentSource):
    async def read(self) -> list[Record]:
        await asyncio.sleep(0.2)
        return []
