"""Source-record tracker: ties async sink completions back to source commits.

Parity: ``SourceRecordTracker``
(``langstream-runtime-impl/.../agent/SourceRecordTracker.java:17,30``): when a
processor emits N result records for one source record, the source record is
committed only after all N are durably written by the sink. Combined with the
consumer's contiguous-prefix commit this yields at-least-once end-to-end.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable

from langstream_tpu.api.record import Record


class SourceRecordTracker:
    def __init__(self, commit: Callable[[list[Record]], Awaitable[None]]):
        self._commit = commit
        self._remaining: dict[int, int] = {}
        self._records: dict[int, Record] = {}
        self._all_done = asyncio.Event()
        self._all_done.set()

    def track(self, source_record: Record, num_results: int) -> None:
        rid = id(source_record)
        self._records[rid] = source_record
        if num_results <= 0:
            # nothing to write: commit immediately
            self._remaining[rid] = 0
        else:
            self._remaining[rid] = num_results
            self._all_done.clear()

    async def commit_if_tracked_empty(self, source_record: Record) -> None:
        rid = id(source_record)
        if self._remaining.get(rid) == 0:
            await self._finish(rid)

    async def record_written(self, source_record: Record) -> None:
        rid = id(source_record)
        if rid not in self._remaining:
            return
        self._remaining[rid] -= 1
        if self._remaining[rid] <= 0:
            await self._finish(rid)

    async def record_failed(self, source_record: Record) -> None:
        """Drop tracking without committing (error path decides the fate)."""
        rid = id(source_record)
        self._remaining.pop(rid, None)
        self._records.pop(rid, None)
        self._maybe_set_done()

    async def commit_now(self, source_record: Record) -> None:
        """Force-commit (skip / dead-letter paths)."""
        rid = id(source_record)
        self._remaining.pop(rid, None)
        record = self._records.pop(rid, source_record)
        await self._commit([record])
        self._maybe_set_done()

    async def _finish(self, rid: int) -> None:
        self._remaining.pop(rid, None)
        record = self._records.pop(rid, None)
        if record is not None:
            await self._commit([record])
        self._maybe_set_done()

    def _maybe_set_done(self) -> None:
        if not any(v > 0 for v in self._remaining.values()):
            self._all_done.set()

    def pending_count(self) -> int:
        return sum(1 for v in self._remaining.values() if v > 0)

    async def wait_for_no_pending(self, timeout: float | None = None) -> bool:
        """Graceful drain (parity: ``AgentRunner.waitForNoPendingRecords``,
        ``AgentRunner.java:562``)."""
        try:
            await asyncio.wait_for(self._all_done.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False
