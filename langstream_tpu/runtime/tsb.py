"""tpustream broker client: the native-broker TopicConnectionsRuntime.

Speaks the tsbroker wire protocol (``langstream_tpu/native/tsbroker.cc``)
over asyncio TCP. Semantics mirror the reference's Kafka runtime:

- consumer groups with broker-driven partition assignment and rebalance
  (parity: ``KafkaConsumerWrapper`` implementing ``ConsumerRebalanceListener``,
  ``langstream-kafka-runtime/.../runner/KafkaConsumerWrapper.java:41``);
- out-of-order ack tracking committing only the longest contiguous prefix
  per partition (parity: ``KafkaConsumerWrapper.java:194-203`` — TreeSet of
  uncommitted offsets);
- position-addressed readers for the gateway (``KafkaReaderWrapper.java``);
- dead-letter producers on ``<topic>-deadletter``
  (``KafkaTopicConnectionsRuntime.java:123``).

Registered as streaming-cluster ``type: tpustream``; config:
``{"bootstrap": "host:port"}`` (or separate host/port keys).
"""

from __future__ import annotations

import asyncio
import base64
import itertools
import json
import socket
import struct
from typing import Any

from langstream_tpu.api.record import Record, SimpleRecord
from langstream_tpu.api.topics import (
    OFFSET_HEADER,
    TopicAdmin,
    TopicConnectionsRuntime,
    TopicConnectionsRuntimeRegistry,
    TopicConsumer,
    TopicOffset,
    TopicProducer,
    TopicReader,
)

OP_PRODUCE = 1
OP_FETCH = 2
OP_COMMIT = 3
OP_COMMITTED = 4
OP_CREATE_TOPIC = 5
OP_DELETE_TOPIC = 6
OP_LIST_TOPICS = 7
OP_JOIN_GROUP = 8
OP_LEAVE_GROUP = 9
OP_PING = 10
OP_OFFSETS = 11

STATUS_OK = 0
STATUS_ERROR = 1
STATUS_REBALANCED = 2

_FETCH_WAIT_MS = 10_000
_MAX_FETCH_RECORDS = 64


# ---------------------------------------------------------------------------
# wire codec


def _p_str(s: str) -> bytes:
    raw = s.encode()
    return struct.pack(">H", len(raw)) + raw


def _p_blob(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


class _Cursor:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def u8(self) -> int:
        v = self.buf[self.pos]
        self.pos += 1
        return v

    def u16(self) -> int:
        (v,) = struct.unpack_from(">H", self.buf, self.pos)
        self.pos += 2
        return v

    def u32(self) -> int:
        (v,) = struct.unpack_from(">I", self.buf, self.pos)
        self.pos += 4
        return v

    def u64(self) -> int:
        (v,) = struct.unpack_from(">Q", self.buf, self.pos)
        self.pos += 8
        return v

    def i64(self) -> int:
        v = self.u64()
        return v - (1 << 64) if v >= (1 << 63) else v

    def str(self) -> str:
        n = self.u16()
        v = self.buf[self.pos : self.pos + n].decode()
        self.pos += n
        return v

    def blob(self) -> bytes:
        n = self.u32()
        v = self.buf[self.pos : self.pos + n]
        self.pos += n
        return v


# ---------------------------------------------------------------------------
# record <-> wire. The full record rides as a JSON envelope in the wire value;
# the wire key carries only the routing key bytes (stable partition hashing
# happens broker-side).


def _tag(value: Any) -> Any:
    if isinstance(value, bytes):
        return {"__b64__": base64.b64encode(value).decode()}
    if isinstance(value, TopicOffset):
        return None  # transport-internal, never serialized
    return value


def _untag(value: Any) -> Any:
    if isinstance(value, dict) and set(value) == {"__b64__"}:
        return base64.b64decode(value["__b64__"])
    return value


def _walk(value: Any, fn) -> Any:
    value = fn(value)
    if isinstance(value, dict):
        return {k: _walk(v, fn) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_walk(v, fn) for v in value]
    return value


def encode_record(record: Record) -> tuple[bytes, bytes]:
    headers = [
        [k, _walk(v, _tag)]
        for k, v in record.headers
        if k != OFFSET_HEADER and not isinstance(v, TopicOffset)
    ]
    envelope = {
        "key": _walk(record.key, _tag),
        "value": _walk(record.value, _tag),
        "headers": headers,
        "origin": record.origin,
        "timestamp": record.timestamp,
    }
    if record.key is None:
        routing = b""
    elif isinstance(record.key, bytes):
        routing = record.key
    elif isinstance(record.key, str):
        routing = record.key.encode()
    else:
        routing = json.dumps(record.key, sort_keys=True).encode()
    return routing, json.dumps(envelope).encode()


def decode_record(value: bytes) -> SimpleRecord:
    env = json.loads(value.decode())
    return SimpleRecord(
        value=_walk(env.get("value"), _untag),
        key=_walk(env.get("key"), _untag),
        headers=tuple((k, _walk(v, _untag)) for k, v in env.get("headers", [])),
        origin=env.get("origin"),
        timestamp=env.get("timestamp"),
    )


def _read_wire_record(cur: "_Cursor") -> tuple[int, SimpleRecord]:
    """Parse one record from a FETCH reply: offset, routing key (the
    authoritative copy lives in the envelope), envelope, wire headers."""
    offset = cur.u64()
    cur.blob()  # routing key
    record = decode_record(cur.blob())
    for _ in range(cur.u16()):  # wire-level headers (unused by this client)
        cur.str()
        cur.blob()
    return offset, record


# ---------------------------------------------------------------------------
# connection


class TsbError(RuntimeError):
    pass


class Rebalanced(Exception):
    """Raised to a fetch waiter when its group generation went stale."""


class TsbConnection:
    """One TCP connection; concurrent requests multiplexed by request id."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._pending: dict[int, asyncio.Future] = {}
        self._ids = itertools.count(1)
        self._pump: asyncio.Task | None = None
        self._closed = False

    async def connect(self) -> "TsbConnection":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )
        sock = self._writer.get_extra_info("socket")
        if sock is not None:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._pump = asyncio.ensure_future(self._pump_loop())
        return self

    async def _pump_loop(self) -> None:
        try:
            while True:
                header = await self._reader.readexactly(4)
                (length,) = struct.unpack(">I", header)
                payload = await self._reader.readexactly(length)
                cur = _Cursor(payload)
                rid = cur.u64()
                status = cur.u8()
                fut = self._pending.pop(rid, None)
                if fut is None or fut.done():
                    continue
                if status == STATUS_ERROR:
                    fut.set_exception(TsbError(cur.str()))
                elif status == STATUS_REBALANCED:
                    fut.set_exception(Rebalanced())
                else:
                    fut.set_result(cur)
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as exc:
            err = ConnectionError(f"tsbroker connection lost: {exc}")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
        except asyncio.CancelledError:
            pass

    async def request(self, opcode: int, body: bytes = b"") -> _Cursor:
        if self._writer is None:
            raise TsbError("not connected")
        rid = next(self._ids)
        payload = struct.pack(">BQ", opcode, rid) + body
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[rid] = fut
        self._writer.write(struct.pack(">I", len(payload)) + payload)
        await self._writer.drain()
        return await fut

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._pump is not None:
            self._pump.cancel()
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass


# ---------------------------------------------------------------------------
# consumer


class _PartitionState:
    """Offset bookkeeping for one assigned partition.

    ``next_fetch`` advances as records are delivered; ``outstanding`` holds
    delivered-but-unacked offsets. The committable watermark is the smallest
    outstanding offset (or ``next_fetch`` when none) — the longest contiguous
    acked prefix, exactly the reference's TreeSet rule
    (``KafkaConsumerWrapper.java:194-203``).
    """

    __slots__ = ("next_fetch", "outstanding", "committed")

    def __init__(self, start: int):
        self.next_fetch = start
        self.outstanding: set[int] = set()
        self.committed = start

    def watermark(self) -> int:
        return min(self.outstanding) if self.outstanding else self.next_fetch


class TsbTopicConsumer(TopicConsumer):
    def __init__(
        self,
        host: str,
        port: int,
        topic: str,
        group: str,
        client_id: str,
        poll_timeout: float = 1.0,
        max_poll_records: int = _MAX_FETCH_RECORDS,
    ):
        self.topic = topic
        self.group = group
        self.client_id = client_id
        self.poll_timeout = poll_timeout
        self.max_poll_records = max_poll_records
        self._conn = TsbConnection(host, port)
        self._generation = 0
        self._parts: dict[int, _PartitionState] = {}
        self._fetches: dict[int, asyncio.Task] = {}
        self._started = False
        self._total_out = 0

    async def start(self) -> None:
        await self._conn.connect()
        await self._join()
        self._started = True

    async def _join(self) -> None:
        cur = await self._conn.request(
            OP_JOIN_GROUP,
            _p_str(self.group) + _p_str(self.topic) + _p_str(self.client_id),
        )
        self._generation = cur.u32()
        assigned = [cur.u32() for _ in range(cur.u32())]
        # Redelivery-on-rebalance: positions reset to the committed offset,
        # in-flight work for revoked partitions is simply dropped.
        for task in self._fetches.values():
            task.cancel()
        self._fetches.clear()
        self._parts = {}
        for pi in assigned:
            cur = await self._conn.request(
                OP_COMMITTED,
                _p_str(self.group) + _p_str(self.topic) + struct.pack(">I", pi),
            )
            committed = cur.i64()
            self._parts[pi] = _PartitionState(max(0, committed))

    def _fetch_body(self, pi: int, state: _PartitionState) -> bytes:
        return (
            _p_str(self.topic)
            + struct.pack(
                ">IQII",
                pi,
                state.next_fetch,
                self.max_poll_records,
                _FETCH_WAIT_MS,
            )
            + _p_str(self.group)
            + struct.pack(">I", self._generation)
        )

    async def read(self) -> list[Record]:
        if not self._started:
            raise TsbError("consumer not started")
        # Keep one long-poll fetch in flight per assigned partition; return
        # as soon as any partition yields records.
        for pi, state in self._parts.items():
            if pi not in self._fetches or self._fetches[pi].done():
                if pi in self._fetches and self._fetches[pi].done():
                    continue  # completed result is harvested below
                self._fetches[pi] = asyncio.ensure_future(
                    self._conn.request(OP_FETCH, self._fetch_body(pi, state))
                )
        if not self._fetches:
            await asyncio.sleep(self.poll_timeout)
            return []
        done, _ = await asyncio.wait(
            self._fetches.values(),
            timeout=self.poll_timeout,
            return_when=asyncio.FIRST_COMPLETED,
        )
        if not done:
            return []
        batch: list[Record] = []
        rebalanced = False
        for pi in list(self._fetches):
            task = self._fetches[pi]
            if not task.done():
                continue
            del self._fetches[pi]
            try:
                cur = task.result()
            except Rebalanced:
                rebalanced = True
                continue
            except asyncio.CancelledError:
                continue
            state = self._parts.get(pi)
            if state is None:
                continue
            for _ in range(cur.u32()):
                offset, record = _read_wire_record(cur)
                if offset < state.next_fetch:
                    continue
                state.next_fetch = offset + 1
                state.outstanding.add(offset)
                batch.append(
                    record.with_headers(
                        {OFFSET_HEADER: TopicOffset(self.topic, pi, offset)}
                    )
                )
        if rebalanced:
            await self._join()
        self._total_out += len(batch)
        return batch

    async def commit(self, records: list[Record]) -> None:
        touched: set[int] = set()
        for record in records:
            offset: TopicOffset | None = record.header(OFFSET_HEADER)
            if offset is None or offset.topic != self.topic:
                continue
            state = self._parts.get(offset.partition)
            if state is None:
                continue  # partition revoked by a rebalance; will redeliver
            state.outstanding.discard(offset.offset)
            touched.add(offset.partition)
        for pi in touched:
            state = self._parts[pi]
            watermark = state.watermark()
            if watermark > state.committed:
                state.committed = watermark
                await self._conn.request(
                    OP_COMMIT,
                    _p_str(self.group)
                    + _p_str(self.topic)
                    + struct.pack(">IQ", pi, watermark),
                )

    async def close(self) -> None:
        if not self._started:
            return
        self._started = False
        for task in self._fetches.values():
            task.cancel()
        self._fetches.clear()
        try:
            await self._conn.request(
                OP_LEAVE_GROUP,
                _p_str(self.group) + _p_str(self.topic) + _p_str(self.client_id),
            )
        except (TsbError, ConnectionError):
            pass
        await self._conn.close()

    def total_out(self) -> int:
        return self._total_out


class TsbTopicProducer(TopicProducer):
    def __init__(self, host: str, port: int, topic: str):
        self.topic = topic
        self._conn = TsbConnection(host, port)
        self._total_in = 0

    async def start(self) -> None:
        await self._conn.connect()

    async def write(self, record: Record) -> None:
        routing, value = encode_record(record)
        await self._conn.request(
            OP_PRODUCE,
            _p_str(self.topic)
            + _p_blob(routing)
            + _p_blob(value)
            + struct.pack(">H", 0),
        )
        self._total_in += 1

    async def close(self) -> None:
        await self._conn.close()

    def total_in(self) -> int:
        return self._total_in


class TsbTopicReader(TopicReader):
    """Position-addressed reader over all partitions (gateway consume path)."""

    def __init__(self, host: str, port: int, topic: str,
                 initial_position: str = "latest"):
        self.topic = topic
        self.initial_position = initial_position
        self._conn = TsbConnection(host, port)
        self._positions: dict[int, int] = {}

    async def start(self) -> None:
        await self._conn.connect()
        cur = await self._conn.request(OP_LIST_TOPICS)
        nparts = 1
        for _ in range(cur.u32()):
            name = cur.str()
            n = cur.u32()
            if name == self.topic:
                nparts = n
        for pi in range(nparts):
            cur = await self._conn.request(
                OP_OFFSETS, _p_str(self.topic) + struct.pack(">I", pi)
            )
            earliest, end = cur.u64(), cur.u64()
            if self.initial_position == "earliest":
                self._positions[pi] = earliest
            elif isinstance(self.initial_position, int):
                self._positions[pi] = self.initial_position
            else:
                self._positions[pi] = end

    async def read(self, timeout: float | None = None) -> list[Record]:
        wait_ms = int((timeout or 0.5) * 1000)
        tasks = {
            pi: asyncio.ensure_future(
                self._conn.request(
                    OP_FETCH,
                    _p_str(self.topic)
                    + struct.pack(
                        ">IQII", pi, pos, _MAX_FETCH_RECORDS, wait_ms
                    )
                    + _p_str("")
                    + struct.pack(">I", 0),
                )
            )
            for pi, pos in self._positions.items()
        }
        if not tasks:
            return []
        await asyncio.wait(tasks.values(), return_when=asyncio.ALL_COMPLETED)
        batch: list[Record] = []
        for pi, task in tasks.items():
            try:
                cur = task.result()
            except (TsbError, Rebalanced):
                continue
            for _ in range(cur.u32()):
                offset, record = _read_wire_record(cur)
                batch.append(record)
                self._positions[pi] = offset + 1
        return batch

    async def close(self) -> None:
        await self._conn.close()


class TsbTopicAdmin(TopicAdmin):
    def __init__(self, host: str, port: int):
        self._conn = TsbConnection(host, port)
        self._connected = False

    async def _ensure(self) -> None:
        if not self._connected:
            await self._conn.connect()
            self._connected = True

    async def create_topic(self, name: str, partitions: int = 1,
                           options: dict | None = None) -> None:
        await self._ensure()
        await self._conn.request(
            OP_CREATE_TOPIC, _p_str(name) + struct.pack(">I", partitions)
        )

    async def delete_topic(self, name: str) -> None:
        await self._ensure()
        await self._conn.request(OP_DELETE_TOPIC, _p_str(name))

    async def close(self) -> None:
        await self._conn.close()


class TsbTopicConnectionsRuntime(TopicConnectionsRuntime):
    """streamingCluster ``type: tpustream``."""

    def __init__(self) -> None:
        self.host = "127.0.0.1"
        self.port = 0
        self._client_seq = itertools.count()

    def init(self, streaming_cluster_configuration: dict[str, Any]) -> None:
        config = streaming_cluster_configuration or {}
        bootstrap = config.get("bootstrap")
        if bootstrap:
            host, _, port = str(bootstrap).rpartition(":")
            self.host, self.port = host or "127.0.0.1", int(port)
        else:
            self.host = config.get("host", "127.0.0.1")
            self.port = int(config.get("port", 0))
        if not self.port:
            raise TsbError(
                "tpustream streaming cluster requires configuration.bootstrap "
                '("host:port") or host/port'
            )

    def create_consumer(
        self, agent_id: str, config: dict[str, Any]
    ) -> TopicConsumer:
        topic = config["topic"]
        group = config.get("group", agent_id or f"group-{topic}")
        client_id = f"{group}-{next(self._client_seq)}"
        return TsbTopicConsumer(self.host, self.port, topic, group, client_id)

    def create_producer(
        self, agent_id: str, config: dict[str, Any]
    ) -> TopicProducer:
        return TsbTopicProducer(self.host, self.port, config["topic"])

    def create_reader(
        self, config: dict[str, Any], initial_position: str = "latest"
    ) -> TopicReader:
        return TsbTopicReader(
            self.host, self.port, config["topic"], initial_position
        )

    def create_topic_admin(self) -> TopicAdmin:
        return TsbTopicAdmin(self.host, self.port)

    def create_deadletter_producer(
        self, agent_id: str, config: dict[str, Any]
    ) -> TopicProducer:
        return TsbTopicProducer(
            self.host, self.port, config["topic"] + "-deadletter"
        )

    async def close(self) -> None:
        pass


TopicConnectionsRuntimeRegistry.register("tpustream", TsbTopicConnectionsRuntime)
