"""The TPU serving engine: continuous batching over a slot-based KV cache.

This is the genuinely new core relative to the reference (SURVEY.md §7 stage
6): where the reference's ``ai-*`` agents call SaaS HTTP APIs, this engine
serves Llama-family decoders and MiniLM-class encoders **in-process on the
pod's chips**: prefill/decode split, slot-based continuous batching (a
request joins the running decode batch as soon as a slot frees), in-jit
sampling (only the sampled token ids cross the host boundary), streaming
detokenisation, and ``NamedSharding`` tensor/data parallelism over ICI
meshes.
"""

from langstream_tpu.serving.engine import (
    ServingConfig,
    TpuServingEngine,
    EmbeddingEngine,
)

__all__ = ["ServingConfig", "TpuServingEngine", "EmbeddingEngine"]
