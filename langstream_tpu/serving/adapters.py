"""Tiered multi-LoRA adapter store: device rows → host-RAM spill →
object storage (docs/ADAPTERS.md, ROADMAP item 4).

LangStream's reference delegates per-tenant model customization to
external APIs; serving it in-tree means ONE fleet must hold thousands
of fine-tunes, not one dense decoder. The engine does that with batched
LoRA (Punica/S-LoRA-style adapter gather): every paged decode/prefill
program carries a stacked per-layer A/B factor buffer of shape
``(layers, n_rows, in, rank)`` / ``(layers, n_rows, rank, out)`` plus a
per-slot ``int32`` row index, so heterogeneous-adapter batches run in
one jitted program — row 0 is all-zeros, which makes adapter-less slots
mathematically the base model. This module owns where those factors
live when they are NOT on device:

- **T0 — device rows**: ``t0-entries`` resident adapters inside the
  stacked buffer (the engine owns the device copies; this store owns
  the row map, the LRU order, and the pin ledger). Rows pinned by
  in-flight requests are NEVER evicted — ``t0_assign`` refuses and the
  admission backpressures instead, so a slot can never decode against
  weights that were swapped under it.
- **T1 — host-RAM spill**: an LRU byte-budgeted map of adapter factor
  arrays keyed by adapter NAME (adapters are named artifacts, not
  content-addressed blocks — a re-published name is a new version, and
  the T2 wire fingerprint is what refuses stale layouts).
- **T2 — object storage**: the origin tier. Factors serialize through
  the kvtransfer ``LSKV`` wire with an adapter fingerprint — base
  model, rank, factor dims, dtype — that a loading replica checks
  exactly like ``/kv/import`` (mismatch → refused AND deleted, never
  half-loaded). A cold replica discovers published adapters by rescan
  and hydrates them T2 → T1 → T0 on first request.

Threading model (graftcheck **LORA1701**, the adapter plane's PFX801
twin): every loop-side resolve/assign/pin/evict decision is wait-free —
GIL-atomic container ops plus arithmetic, no locks, no I/O, no device
syncs — because it runs at the engine loop's safe point on the
admission path. The ONLY blocking work is T2 object-storage I/O, exempt
by design on the background **hydrator thread** (``_io_*`` methods);
the loop talks to it exclusively through handoff deques and applies
results back at the next safe point. Byte ledgers are single-writer
(loop-side) and sum exactly; loss is counted, never silent.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable

import numpy as np

from langstream_tpu.serving.kvtransfer import (
    LayoutMismatch,
    deserialize_handoff,
    serialize_handoff,
)
from langstream_tpu.serving.prefixstore import PrefixStorage, make_prefix_storage

log = logging.getLogger(__name__)

#: blob kind stamped into every T2 header — an adapter blob is neither a
#: prefix block nor a request handoff, and every import path must be
#: able to tell the three apart
BLOB_KIND = "lora-adapter"

#: record header naming the adapter a request wants; the gateway stamps
#: it from QoS tenant config and the router pins adapter→replica
#: affinity on it (beside the prefix-digest pins)
ADAPTER_HEADER = "langstream-adapter"

#: the eight LoRA factor arrays every adapter ships — A/B pairs for the
#: four attention projections (deltas on wq/wk/wv/wo; ffn deltas are a
#: future leg). Shapes (per key, leading ``layers`` axis):
#:   wq_a (L, hidden, rank)    wq_b (L, rank, q_dim)
#:   wk_a (L, hidden, rank)    wk_b (L, rank, kv_dim)
#:   wv_a (L, hidden, rank)    wv_b (L, rank, kv_dim)
#:   wo_a (L, q_dim, rank)     wo_b (L, rank, hidden)
#: The LoRA alpha/rank scale is folded into the B factors at publish
#: time, so application is always plain ``h @ A @ B``.
FACTOR_KEYS = (
    "wq_a", "wq_b", "wk_a", "wk_b",
    "wv_a", "wv_b", "wo_a", "wo_b",
)


def check_adapter_name(name: str) -> str:
    """Adapter names are storage keys and metric labels: short, no
    path/meta characters. Raises ValueError on anything else."""
    if not isinstance(name, str) or not name or len(name) > 120:
        raise ValueError(f"illegal adapter name {name!r}")
    ok = set("abcdefghijklmnopqrstuvwxyz"
             "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-")
    if not set(name) <= ok:
        raise ValueError(
            f"adapter name {name!r} may only contain [A-Za-z0-9_-]"
        )
    return name


def check_adapter_fingerprint(
    ours: dict[str, Any], theirs: dict[str, Any]
) -> None:
    """Raise :class:`LayoutMismatch` naming every disagreeing key. All
    of OUR keys must match (kvtransfer's check compares a fixed KV
    layout key set; adapter fingerprints carry their own vocabulary —
    base-model, rank, factor dims, dtype)."""
    bad = [k for k in ours if ours.get(k) != theirs.get(k)]
    if bad:
        detail = ", ".join(
            f"{k}: ours={ours.get(k)!r} theirs={theirs.get(k)!r}"
            for k in sorted(bad)
        )
        raise LayoutMismatch(f"adapter fingerprint mismatch ({detail})")


# ---------------------------------------------------------------------------
# spec (the `adapter-store` section of tpu-serving-configuration)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AdapterStoreSpec:
    """Frozen, hashable tier policy (rides :class:`ServingConfig`, same
    kebab ``to_dict``/``from_dict`` round-trip and deploy-time
    validation contract as the prefix-store/qos/slo specs)."""

    enabled: bool = True
    # LoRA rank every adapter in this fleet must ship (one stacked
    # device buffer → one rank; mixed-rank fleets deploy per-rank pools)
    rank: int = 8
    # device-resident adapter rows (row 0 is the reserved zeros row for
    # adapter-less slots and is NOT counted here)
    t0_entries: int = 4
    # T1 host-RAM budget (LRU past it; overflow demotes to T2 when one
    # is configured, else evicts — counted, never silent)
    t1_bytes: int = 256 << 20
    # T2 object-storage budget; None = unbudgeted
    t2_bytes: int | None = None
    # T2 backend config as sorted (key, value) pairs so the spec stays
    # hashable; () disables T2. Schema shared with the prefix store
    # (:func:`make_prefix_storage`) — point it at a DIFFERENT path or
    # key-prefix than the prefix tier.
    t2: tuple[tuple[str, str], ...] = ()
    # how long an admission may wait for a T2 hydration before the
    # request is refused cold (unlike a prefix miss there is no
    # recompute fallback — the weights either arrive or the request
    # fails loudly)
    hydrate_timeout_s: float = 5.0
    # hydrator-thread T2 index rescan period (how quickly this replica
    # notices adapters published by others)
    t2_rescan_s: float = 5.0

    def t2_config(self) -> dict[str, str] | None:
        return dict(self.t2) if self.t2 else None

    def to_dict(self) -> dict[str, Any]:
        return {
            "enabled": self.enabled,
            "rank": self.rank,
            "t0-entries": self.t0_entries,
            "t1-bytes": self.t1_bytes,
            "t2-bytes": self.t2_bytes,
            "t2": self.t2_config(),
            "hydrate-timeout-s": self.hydrate_timeout_s,
            "t2-rescan-s": self.t2_rescan_s,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any] | None) -> "AdapterStoreSpec | None":
        if d is None:
            return None
        if not isinstance(d, dict):
            raise ValueError("adapter-store section must be a mapping")
        known = {
            "enabled", "rank", "t0-entries", "t0_entries",
            "t1-bytes", "t1_bytes", "t2-bytes", "t2_bytes", "t2",
            "hydrate-timeout-s", "hydrate_timeout_s",
            "t2-rescan-s", "t2_rescan_s",
        }
        unknown = sorted(set(d) - known)
        if unknown:
            raise ValueError(f"unknown adapter-store keys: {unknown}")
        rank = int(d.get("rank", cls.rank))
        if rank <= 0:
            raise ValueError("adapter-store rank must be > 0")
        t0 = int(d.get("t0-entries", d.get("t0_entries", cls.t0_entries)))
        if t0 <= 0:
            raise ValueError("adapter-store t0-entries must be > 0")
        t1 = int(d.get("t1-bytes", d.get("t1_bytes", cls.t1_bytes)))
        if t1 <= 0:
            raise ValueError("adapter-store t1-bytes must be > 0")
        t2_bytes = d.get("t2-bytes", d.get("t2_bytes"))
        if t2_bytes is not None:
            t2_bytes = int(t2_bytes)
            if t2_bytes < 0:
                raise ValueError("adapter-store t2-bytes must be >= 0")
        t2_cfg = d.get("t2")
        t2: tuple[tuple[str, str], ...] = ()
        if t2_cfg:
            if not isinstance(t2_cfg, dict):
                raise ValueError("adapter-store t2 must be a mapping")
            t2_type = str(t2_cfg.get("type", "local"))
            if t2_type not in ("local", "s3"):
                raise ValueError(
                    f"unknown adapter-store t2 type {t2_type!r} "
                    f"(known: local, s3)"
                )
            t2 = tuple(sorted((str(k), str(v)) for k, v in t2_cfg.items()))
        hydrate = float(
            d.get("hydrate-timeout-s",
                  d.get("hydrate_timeout_s", cls.hydrate_timeout_s))
        )
        rescan = float(
            d.get("t2-rescan-s", d.get("t2_rescan_s", cls.t2_rescan_s))
        )
        if hydrate <= 0 or rescan <= 0:
            raise ValueError(
                "adapter-store hydrate-timeout-s and t2-rescan-s must be > 0"
            )
        enabled = d.get("enabled", True)
        if isinstance(enabled, str):
            enabled = enabled.strip().lower() in ("1", "true", "yes", "on")
        return cls(
            enabled=bool(enabled),
            rank=rank,
            t0_entries=t0,
            t1_bytes=t1,
            t2_bytes=t2_bytes,
            t2=t2,
            hydrate_timeout_s=hydrate,
            t2_rescan_s=rescan,
        )


def validate_application_adapter_store(application) -> None:
    """Deploy-time validation: parse every ``tpu-serving-configuration``
    resource's ``adapter-store`` section so a malformed tier policy
    fails the deploy (HTTP 400) instead of the first request."""
    for name, res in (getattr(application, "resources", None) or {}).items():
        if getattr(res, "type", None) != "tpu-serving-configuration":
            continue
        try:
            AdapterStoreSpec.from_dict(
                (res.configuration or {}).get("adapter-store")
            )
        except ValueError as e:
            raise ValueError(
                f"resource {name!r}: invalid adapter-store section: {e}"
            ) from e


# ---------------------------------------------------------------------------
# the tier store
# ---------------------------------------------------------------------------


class AdapterStore:
    """T0 row map + T1 host-RAM spill + T2 object-storage hydration for
    named LoRA adapters, with exact byte ledgers.

    Single-writer discipline (the prefix store's, verbatim): ALL
    ledger/counter/tier mutations happen on the engine-loop side; the
    hydrator thread only performs storage I/O on job payloads and hands
    results back through ``_results``. Loop-side paths are wait-free
    (LORA1701) and the ledgers exactly sum — no second writer to race.

    Conservation invariant (pinned by the property test)::

        t1_bytes + in_transit_bytes + t2_bytes
            == inserted + discovered - evicted

    T0 is a COPY tier — loading a row copies the T1 factors to device
    without moving host bytes, so it has its own resident ledger
    (``len(_t0) × entry_bytes``) outside the conservation equation, and
    its evictions (``t0_evictions``) just free a row.
    """

    #: max fetch/put jobs queued before new demotions evict instead
    #: (backpressure: a dead backend must not grow host memory)
    MAX_PENDING_JOBS = 256

    def __init__(
        self,
        spec: AdapterStoreSpec,
        *,
        fingerprint: dict[str, Any],
        entry_bytes: int,
        clock: Callable[[], float] = time.monotonic,
        fault_injector=None,
    ):
        self.spec = spec
        # network fault seam (serving/faults.py `t2-get` site — shared
        # with the prefix hydrator: both are tier-hydrator object-
        # storage fetches). None in production.
        self._fault_injector = fault_injector
        self.fingerprint = dict(fingerprint)
        # every adapter in a fleet has identical factor shapes (the
        # fingerprint enforces it), so T0 residency is exact arithmetic
        self.entry_bytes = int(entry_bytes)
        self._clock = clock
        # T0: name -> device row (1-based; row 0 is the zeros row).
        # Insertion order = LRU; move_to_end on hit.
        self._t0: "OrderedDict[str, int]" = OrderedDict()
        self._rows_free: list[int] = list(range(spec.t0_entries, 0, -1))
        # name -> in-flight request pin count; pinned rows are never
        # evicted (the refusal the issue's ledger contract names)
        self._pins: dict[str, int] = {}
        # T1: name -> {"arrays": {factor: np}, "nbytes", "pinned_m"}
        self._t1: "OrderedDict[str, dict[str, Any]]" = OrderedDict()
        self.t1_bytes = 0
        # demotions being serialized/PUT on the hydrator (bytes stay
        # accounted until the put confirms — never in two tiers at once)
        self._t2_inflight: dict[str, dict[str, Any]] = {}
        self.in_transit_bytes = 0
        # T2 index: name -> payload bytes (0 = discovered via scan, size
        # unknown until hydrated); insertion order = age for trims
        self._t2_index: "OrderedDict[str, int]" = OrderedDict()
        self.t2_bytes = 0
        self.t2_blob_bytes = 0
        # names with an in-flight T2 fetch (dedup + completion check)
        self._hydrating: dict[str, float] = {}
        # loop-side event feed for the engine's flight recorder
        self._events: deque = deque()
        # monotone counters (conservation terms + tier hit/miss)
        self.inserted_bytes = 0
        self.hydrated_bytes = 0
        self.discovered_bytes = 0
        self.evicted_bytes = 0
        self.t0_hits = 0
        self.t1_hits = 0
        self.t1_misses = 0
        self.t2_hits = 0
        self.loads = 0
        self.installs = 0
        self.demotions_t1_t2 = 0
        self.hydrations = 0
        self.hydrate_failures = 0
        self.fingerprint_refusals = 0
        self.evictions = 0
        self.t0_evictions = 0
        self.eviction_refusals = 0
        self.scans = 0
        # hydrator plumbing: handoff deques + a kick event; the thread
        # starts only when a T2 backend is configured
        self._jobs: deque = deque()
        self._results: deque = deque()
        self._kick = threading.Event()
        self._storage = make_prefix_storage(spec.t2_config())
        self._thread: threading.Thread | None = None
        if self._storage is not None:
            self._jobs.append(("scan",))
            self._thread = threading.Thread(
                target=self._io_loop, name="adapter-hydrator", daemon=True
            )
            self._thread.start()

    # -- wait-free decision paths (LORA1701) -----------------------------

    def t0_row(self, name: str) -> int | None:
        """Device row for a resident adapter (LRU bump) or None."""
        row = self._t0.get(name)
        if row is None:
            return None
        self._t0.move_to_end(name)
        self.t0_hits += 1
        return row

    def t0_resident(self) -> dict[str, int]:
        """Snapshot of the resident row map (stats/panel surface)."""
        return dict(self._t0)

    def pin(self, name: str) -> None:
        """Count one in-flight request against the adapter's row; a
        pinned row is refused eviction until every pin releases."""
        self._pins[name] = self._pins.get(name, 0) + 1

    def unpin(self, name: str) -> None:
        n = self._pins.get(name, 0) - 1
        if n <= 0:
            self._pins.pop(name, None)
        else:
            self._pins[name] = n

    def pinned(self, name: str) -> int:
        return self._pins.get(name, 0)

    def t0_assign(self, name: str) -> int | None:
        """Pick a device row for ``name``: a free row, else evict the
        LRU unpinned resident. Returns None when every resident row is
        pinned by in-flight requests — the eviction is REFUSED and the
        caller backpressures (admission retries next pass). The engine
        owns the actual device copy; it calls :meth:`note_loaded` after
        the copy lands."""
        row = self._t0.get(name)
        if row is not None:
            self._t0.move_to_end(name)
            return row
        if self._rows_free:
            row = self._rows_free.pop()
        else:
            victim = None
            for resident in self._t0:  # LRU order
                if self._pins.get(resident, 0) == 0:
                    victim = resident
                    break
            if victim is None:
                self.eviction_refusals += 1
                return None
            row = self._t0.pop(victim)
            self.t0_evictions += 1
            self._events.append(
                (
                    "adapter-evict",
                    {
                        "tier": "t0",
                        "adapter": victim,
                        "row": row,
                        "reason": "t0-capacity",
                    },
                )
            )
        self._t0[name] = row
        return row

    def note_loaded(self, name: str, row: int, device_ms: float = 0.0) -> None:
        """Bookkeeping for a completed T1→T0 device copy (the engine
        owns the copy; the store only counts it)."""
        self.loads += 1
        self._events.append(
            ("adapter-load",
             {"adapter": name, "row": row,
              "bytes": self.entry_bytes,
              "device_ms": round(device_ms, 3)})
        )

    def t1_has(self, name: str) -> bool:
        return name in self._t1

    def t2_has(self, name: str) -> bool:
        """Wait-free T2 membership: the in-memory index maintained by
        put confirmations and hydrator rescans — never storage I/O."""
        return name in self._t2_index or name in self._t2_inflight

    def hydrating(self, name: str) -> bool:
        return name in self._hydrating

    def known(self, name: str) -> bool:
        """Is the adapter anywhere in the tier chain? False means a
        request naming it is refused cold (nothing to wait for)."""
        return (
            name in self._t0
            or name in self._t1
            or self.t2_has(name)
            or name in self._hydrating
        )

    def t1_peek(self, name: str) -> dict[str, Any] | None:
        """T1 entry for a device load (LRU bump, NOT removed — T0 is a
        copy tier, so the host bytes stay in T1 under its own budget).
        Counts a hit or a miss; a miss returns None."""
        entry = self._t1.get(name)
        if entry is None:
            self.t1_misses += 1
            return None
        self._t1.move_to_end(name)
        self.t1_hits += 1
        return entry

    def install(self, name: str, arrays: dict[str, np.ndarray]) -> None:
        """Directly insert adapter factors into T1 (local load path:
        tests, bench seeding, a sidecar that fetched out-of-band).
        Overwrites an existing version of the same name."""
        check_adapter_name(name)
        missing = sorted(set(FACTOR_KEYS) - set(arrays))
        if missing:
            raise ValueError(f"adapter {name!r} missing factors {missing}")
        old = self._t1.pop(name, None)
        if old is not None:
            self.t1_bytes -= old["nbytes"]
            self.evicted_bytes += old["nbytes"]
            self.evictions += 1
        self.installs += 1
        self._insert_t1(name, arrays, source="local")

    def _insert_t1(
        self,
        name: str,
        arrays: dict[str, np.ndarray],
        *,
        source: str,
    ) -> None:
        """Insert one installed/hydrated adapter into T1 (loop-side).
        Past the byte budget the LRU tail demotes to T2 (when
        configured) or evicts — counted and evented either way."""
        if name in self._t1:
            return  # already resident (idempotent re-insert)
        nbytes = int(sum(a.nbytes for a in arrays.values()))
        self._t1[name] = {
            "arrays": arrays,
            "nbytes": nbytes,
            # hydrated entries are PINNED against the budget shrink for
            # one hydrate-timeout window: the admission that asked for
            # them loads them to a device row within it, and without
            # the pin a tight T1 budget would evict the hydration
            # before the requeued request saw it (hydrate → evict →
            # re-hydrate livelock). Expired pins shrink normally.
            "pinned_m": self._clock() if source == "t2" else None,
        }
        self.t1_bytes += nbytes
        self.inserted_bytes += nbytes
        self._shrink_t1()

    def _shrink_t1(self) -> None:
        """Eviction decision for the T1 byte budget (wait-free: the LRU
        walk is dict arithmetic; demotion I/O happens later on the
        hydrator)."""
        while self.t1_bytes > self.spec.t1_bytes and self._t1:
            victim = None
            now = self._clock()
            for name, entry in self._t1.items():  # LRU order
                pinned = entry.get("pinned_m")
                if (
                    pinned is not None
                    and now - pinned < self.spec.hydrate_timeout_s
                ):
                    continue
                victim = name
                break
            if victim is None:
                # everything live-pinned by in-flight hydrations: allow
                # the bounded overshoot and let the pins expire
                return
            name = victim
            entry = self._t1.pop(victim)
            self.t1_bytes -= entry["nbytes"]
            if (
                self._storage is not None
                and name not in self._t2_index
                and name not in self._t2_inflight
                and len(self._jobs) < self.MAX_PENDING_JOBS
            ):
                self._t2_inflight[name] = entry
                self.in_transit_bytes += entry["nbytes"]
                self.demotions_t1_t2 += 1
                self._jobs.append(("put", name, entry))
                self._kick.set()
                self._events.append(
                    (
                        "adapter-demote",
                        {
                            "tier": "t1->t2",
                            "adapter": name,
                            "bytes": entry["nbytes"],
                        },
                    )
                )
            else:
                reason = (
                    "already-in-t2"
                    if name in self._t2_index or name in self._t2_inflight
                    else ("t1-budget" if self._storage is None
                          else "hydrator-backlog")
                )
                # a copy already durable in T2 is dropped, not lost
                self.evictions += 1
                self.evicted_bytes += entry["nbytes"]
                self._events.append(
                    (
                        "adapter-evict",
                        {
                            "tier": "t1",
                            "adapter": name,
                            "bytes": entry["nbytes"],
                            "reason": reason,
                        },
                    )
                )

    def request_hydration(self, names: list[str]) -> int:
        """Enqueue T2→T1 fetches for the named adapters (dedup'd,
        backpressured). Returns how many fetches are now pending — 0
        means nothing to wait for."""
        pending = 0
        for name in names:
            if name in self._t1:
                continue
            if name in self._hydrating:
                pending += 1
                continue
            if name not in self._t2_index:
                continue
            if len(self._jobs) >= self.MAX_PENDING_JOBS:
                break
            self._hydrating[name] = self._clock()
            self._jobs.append(("fetch", name))
            pending += 1
        if pending:
            self._kick.set()
        return pending

    def apply_results(self) -> None:
        """Drain the hydrator's result deque and apply ledger moves +
        T1 inserts on the loop side (the single writer). Wait-free:
        container ops and arithmetic over already-fetched payloads."""
        while self._results:
            result = self._results.popleft()
            kind = result[0]
            if kind == "put-done":
                _, name, blob_bytes = result
                entry = self._t2_inflight.pop(name, None)
                if entry is None:
                    continue
                self.in_transit_bytes -= entry["nbytes"]
                self._t2_index[name] = entry["nbytes"]
                self.t2_bytes += entry["nbytes"]
                self.t2_blob_bytes += blob_bytes
                self._trim_t2()
            elif kind == "put-failed":
                _, name, error = result
                entry = self._t2_inflight.pop(name, None)
                if entry is None:
                    continue
                self.in_transit_bytes -= entry["nbytes"]
                self.evictions += 1
                self.evicted_bytes += entry["nbytes"]
                self._events.append(
                    (
                        "adapter-evict",
                        {
                            "tier": "t1->t2",
                            "adapter": name,
                            "bytes": entry["nbytes"],
                            "reason": f"put-failed: {error}"[:120],
                        },
                    )
                )
            elif kind == "fetch-done":
                _, name, arrays, nbytes = result
                self._hydrating.pop(name, None)
                known = self._t2_index.get(name)
                if known == 0:
                    # discovered via scan: size learned at first fetch
                    self._t2_index[name] = nbytes
                    self.t2_bytes += nbytes
                    self.discovered_bytes += nbytes
                self.t2_hits += 1
                self.hydrations += 1
                if name not in self._t1:
                    self.hydrated_bytes += nbytes
                    self._events.append(
                        (
                            "adapter-hydrate",
                            {
                                "stage": "fetched",
                                "adapter": name,
                                "bytes": nbytes,
                            },
                        )
                    )
                    self._insert_t1(name, arrays, source="t2")
            elif kind == "fetch-refused":
                _, name, error = result
                self._hydrating.pop(name, None)
                dropped = self._t2_index.pop(name, None)
                if dropped:
                    self.t2_bytes -= dropped
                    self.evicted_bytes += dropped
                self.fingerprint_refusals += 1
                self.hydrate_failures += 1
                self.evictions += 1
                self._events.append(
                    (
                        "adapter-evict",
                        {
                            "tier": "t2",
                            "adapter": name,
                            "bytes": dropped or 0,
                            "reason": f"fingerprint-refused: {error}"[:160],
                        },
                    )
                )
            elif kind == "fetch-missing":
                _, name = result
                self._hydrating.pop(name, None)
                dropped = self._t2_index.pop(name, None)
                if dropped:
                    self.t2_bytes -= dropped
                    self.evicted_bytes += dropped
                self.hydrate_failures += 1
            elif kind == "scan-done":
                _, keys = result
                self.scans += 1
                for key in keys:
                    if (
                        key not in self._t2_index
                        and key not in self._t2_inflight
                    ):
                        # size unknown until first hydration (0-byte
                        # placeholder keeps the conservation equation
                        # exact: discovered bytes count when learned)
                        self._t2_index[key] = 0
                dead = [
                    k for k, n in self._t2_index.items()
                    if k not in keys and k not in self._hydrating
                ]
                for k in dead:
                    n = self._t2_index.pop(k)
                    if n:
                        self.t2_bytes -= n
                        self.evicted_bytes += n
                        self.evictions += 1

    def _trim_t2(self) -> None:
        """T2 byte-budget decision (wait-free; deletions are hydrator
        jobs). Oldest-first, never an entry being hydrated."""
        if self.spec.t2_bytes is None:
            return
        for name in list(self._t2_index):
            if self.t2_bytes <= self.spec.t2_bytes:
                break
            if name in self._hydrating:
                continue
            nbytes = self._t2_index.pop(name)
            self.t2_bytes -= nbytes
            self.evictions += 1
            self.evicted_bytes += nbytes
            self._jobs.append(("delete", name))
            self._kick.set()
            self._events.append(
                (
                    "adapter-evict",
                    {
                        "tier": "t2",
                        "adapter": name,
                        "bytes": nbytes,
                        "reason": "t2-budget",
                    },
                )
            )

    def drain_events(self) -> list[tuple[str, dict[str, Any]]]:
        """Pop the pending flight-event feed (loop-side emitter)."""
        out = []
        while self._events:
            out.append(self._events.popleft())
        return out

    def ledger(self) -> dict[str, Any]:
        """The exact byte ledger + conservation terms (wait-free)."""
        return {
            "t0_bytes": len(self._t0) * self.entry_bytes,
            "t1_bytes": self.t1_bytes,
            "in_transit_bytes": self.in_transit_bytes,
            "t2_bytes": self.t2_bytes,
            "t2_blob_bytes": self.t2_blob_bytes,
            "inserted_bytes": self.inserted_bytes,
            "hydrated_bytes": self.hydrated_bytes,
            "discovered_bytes": self.discovered_bytes,
            "evicted_bytes": self.evicted_bytes,
        }

    def stats(self) -> dict[str, Any]:
        return {
            "t0": {
                "entries": len(self._t0),
                "budget_entries": self.spec.t0_entries,
                "bytes": len(self._t0) * self.entry_bytes,
                "budget_bytes": self.spec.t0_entries * self.entry_bytes,
                "resident": sorted(self._t0),
                "pinned": {k: v for k, v in sorted(self._pins.items())},
                "hits": self.t0_hits,
                "loads": self.loads,
                "evictions": self.t0_evictions,
                "eviction_refusals": self.eviction_refusals,
            },
            "t1": {
                "entries": len(self._t1),
                "bytes": self.t1_bytes,
                "budget_bytes": self.spec.t1_bytes,
                "hits": self.t1_hits,
                "misses": self.t1_misses,
            },
            "t2": {
                "enabled": self._storage is not None,
                "entries": len(self._t2_index),
                "bytes": self.t2_bytes,
                "blob_bytes": self.t2_blob_bytes,
                "budget_bytes": self.spec.t2_bytes,
                "hits": self.t2_hits,
                "in_transit_bytes": self.in_transit_bytes,
                "pending_jobs": len(self._jobs),
                "scans": self.scans,
            },
            "rank": self.spec.rank,
            "entry_bytes": self.entry_bytes,
            # the thrash-analysis window (tools/engine_top.py --analyze
            # and the adapter-storm breach predicate both count same-
            # adapter evictions inside one hydrate window)
            "hydrate_timeout_s": self.spec.hydrate_timeout_s,
            "installs": self.installs,
            "demotions_t1_t2": self.demotions_t1_t2,
            "hydrations": self.hydrations,
            "hydrating": len(self._hydrating),
            "hydrate_failures": self.hydrate_failures,
            "fingerprint_refusals": self.fingerprint_refusals,
            "evictions": self.evictions,
            "ledger": self.ledger(),
        }

    # -- hydrator thread (T2 I/O — exempt from LORA1701 by design) ------

    def _io_loop(self) -> None:
        storage = self._storage
        assert storage is not None
        while True:
            if not self._jobs:
                kicked = self._kick.wait(timeout=self.spec.t2_rescan_s)
                self._kick.clear()
                if not kicked:
                    # periodic rescan: notice adapters OTHER replicas
                    # (or an offline publisher) wrote
                    self._io_scan(storage)
                    continue
            try:
                job = self._jobs.popleft()
            except IndexError:
                continue
            kind = job[0]
            if kind == "stop":
                return
            if kind == "sync":
                job[1].set()
            elif kind == "scan":
                self._io_scan(storage)
            elif kind == "put":
                self._io_put(storage, job[1], job[2])
            elif kind == "fetch":
                self._io_fetch(storage, job[1])
            elif kind == "delete":
                try:
                    storage.delete(job[1])
                except Exception as e:
                    # budget trims are best-effort: the ledger already
                    # dropped the entry and counted the bytes
                    log.debug("adapter T2 delete failed: %s", e)

    def _io_scan(self, storage: PrefixStorage) -> None:
        try:
            keys = storage.list_keys()
        except Exception as e:
            log.debug("adapter T2 scan failed: %s", e)
            return
        self._results.append(("scan-done", keys))

    def _io_put(
        self, storage: PrefixStorage, name: str, entry: dict[str, Any]
    ) -> None:
        try:
            blob = serialize_adapter(
                name, entry["arrays"], self.fingerprint
            )
            storage.put(name, blob)
        except Exception as e:
            self._results.append(("put-failed", name, str(e)))
            return
        self._results.append(("put-done", name, len(blob)))

    def _io_fetch(self, storage: PrefixStorage, name: str) -> None:
        if self._fault_injector is not None:
            action = self._fault_injector.fire("t2-get")
            if action is not None:
                # hydrator thread: stalls/drops here never touch the
                # engine loop — a drop reports fetch-missing (the blob
                # "vanished"), the timeout machinery does the rest
                self._events.append(
                    ("fault-injected",
                     {"site": "t2-get", "shape": action.shape,
                      "fire": action.seq})
                )
                if action.shape == "delay-ms":
                    time.sleep(action.hang_ms / 1000.0)
                elif action.shape in ("drop", "error", "oom", "hang"):
                    self._results.append(("fetch-missing", name))
                    return
        try:
            blob = storage.get(name)
        except Exception:
            blob = None
        if blob is None:
            self._results.append(("fetch-missing", name))
            return
        try:
            arrays = deserialize_adapter(blob, name, self.fingerprint)
            nbytes = int(sum(a.nbytes for a in arrays.values()))
        except LayoutMismatch as e:
            # refused AND deleted — a mismatched blob must never be
            # half-loaded, and leaving it would refuse forever
            try:
                storage.delete(name)
            except Exception as delete_error:
                log.debug(
                    "adapter T2 refused-blob delete failed: %s", delete_error
                )
            self._results.append(("fetch-refused", name, str(e)))
            return
        except Exception as e:
            self._results.append(("fetch-refused", name, str(e)))
            return
        self._results.append(("fetch-done", name, arrays, nbytes))

    # -- lifecycle -------------------------------------------------------

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until every queued hydrator job has been processed
        (tests/bench only — never called on the engine loop). Returns
        False on timeout or when no hydrator runs."""
        if self._thread is None:
            return False
        done = threading.Event()
        self._jobs.append(("sync", done))
        self._kick.set()
        return done.wait(timeout_s)

    def close(self) -> None:
        if self._thread is not None:
            self._jobs.append(("stop",))
            self._kick.set()
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._storage is not None:
            self._storage.close()


# ---------------------------------------------------------------------------
# wire helpers (LSKV adapter blobs) + offline publish/merge utilities
# ---------------------------------------------------------------------------


def serialize_adapter(
    name: str,
    arrays: dict[str, np.ndarray],
    fingerprint: dict[str, Any],
) -> bytes:
    """Pack one adapter's factors into the kvtransfer LSKV wire with
    the adapter header (kind, name, fingerprint)."""
    nbytes = int(sum(np.asarray(a).nbytes for a in arrays.values()))
    header = {
        "kind": BLOB_KIND,
        "name": name,
        "fingerprint": dict(fingerprint),
        "payload-bytes": nbytes,
    }
    return serialize_handoff(header, {k: np.asarray(v) for k, v in arrays.items()})


def deserialize_adapter(
    blob: bytes, name: str, fingerprint: dict[str, Any]
) -> dict[str, np.ndarray]:
    """Unpack + verify one adapter blob: kind, name-vs-key, fingerprint
    and factor-set checks all raise :class:`LayoutMismatch` (the caller
    refuses AND deletes). Returns contiguous host copies."""
    header, arrays = deserialize_handoff(blob)
    if header.get("kind") != BLOB_KIND:
        raise LayoutMismatch(
            f"not a lora-adapter blob (kind={header.get('kind')!r})"
        )
    if header.get("name") != name:
        raise LayoutMismatch(
            f"blob name {header.get('name')!r} does not match its key {name!r}"
        )
    check_adapter_fingerprint(fingerprint, header.get("fingerprint") or {})
    missing = sorted(set(FACTOR_KEYS) - set(arrays))
    if missing:
        raise LayoutMismatch(f"adapter blob missing factors {missing}")
    # contiguous host copies: frombuffer views over the blob would pin
    # the whole payload per array
    return {k: np.ascontiguousarray(arrays[k]) for k in FACTOR_KEYS}


def publish_adapter(
    t2_config: dict[str, Any],
    name: str,
    arrays: dict[str, np.ndarray],
    fingerprint: dict[str, Any],
) -> int:
    """Offline publish path (training jobs, tests, bench seeding):
    serialize the factors and PUT them into the T2 origin so replicas
    discover them by rescan. Returns the blob size in bytes."""
    check_adapter_name(name)
    storage = make_prefix_storage(dict(t2_config))
    if storage is None:
        raise ValueError("publish_adapter requires a t2 storage config")
    try:
        blob = serialize_adapter(name, arrays, fingerprint)
        storage.put(name, blob)
    finally:
        storage.close()
    return len(blob)


def make_lora_arrays(
    *,
    layers: int,
    hidden: int,
    heads: int,
    kv_heads: int,
    head_dim: int,
    rank: int,
    seed: int,
    scale: float = 0.02,
    dtype=np.float32,
) -> dict[str, np.ndarray]:
    """Deterministic random LoRA factors for tests/bench (seeded, so a
    cross-replica run can regenerate identical adapters). The alpha/rank
    scale is already folded into the B factors — application is plain
    ``h @ A @ B``."""
    rng = np.random.default_rng(seed)
    q_dim = heads * head_dim
    kv_dim = kv_heads * head_dim

    def _pair(d_in: int, d_out: int, a_key: str, b_key: str):
        a = rng.standard_normal((layers, d_in, rank)) * (1.0 / np.sqrt(d_in))
        b = rng.standard_normal((layers, rank, d_out)) * scale
        return {a_key: a.astype(dtype), b_key: b.astype(dtype)}

    out: dict[str, np.ndarray] = {}
    out.update(_pair(hidden, q_dim, "wq_a", "wq_b"))
    out.update(_pair(hidden, kv_dim, "wk_a", "wk_b"))
    out.update(_pair(hidden, kv_dim, "wv_a", "wv_b"))
    out.update(_pair(q_dim, hidden, "wo_a", "wo_b"))
    return out


def merge_adapter_into_params(
    params: dict[str, Any], arrays: dict[str, np.ndarray]
) -> dict[str, Any]:
    """Offline-merged reference weights ``W + A @ B`` for the
    correctness pin: a single-adapter batched run must be byte-identical
    (greedy, f32) to the base model with the deltas merged in."""
    layers = dict(params["layers"])
    for proj in ("wq", "wk", "wv", "wo"):
        w = np.asarray(layers[proj])
        a = np.asarray(arrays[f"{proj}_a"], dtype=w.dtype)
        b = np.asarray(arrays[f"{proj}_b"], dtype=w.dtype)
        delta = np.einsum("lir,lro->lio", a, b).astype(w.dtype)
        layers[proj] = w + delta
    out = dict(params)
    out["layers"] = layers
    return out


class AdapterUnavailable(RuntimeError):
    """A request named an adapter the serving tier chain cannot
    produce — unknown name, hydration timeout, or hydration failure.
    Refused loudly: unlike a prefix miss there is no recompute
    fallback."""
