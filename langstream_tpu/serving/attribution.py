"""Device attribution plane: per-program cost ledger + HBM memory ledger.

The flight recorder (PR 3) and the health/SLO plane (PR 8) decompose
*wall* time exactly — but BENCH_r05's 40.6 ms/step against an 11.8 ms
roofline (hbm_utilization 0.291) is a *device-side* gap, and one blended
roofline number cannot say which program, which phase of that program,
or which resident bytes own it. This module is the ledger that turns the
one-number roofline into per-program, per-owner truth — the TPU-native
analogue of the LangStream reference's per-agent runtime servlet
(``AgentInfoServlet``), but for XLA programs instead of JVM stats.

Two ledgers, one contract:

**Program cost ledger** (:class:`ProgramLedger`): for every jitted
serving variant the engine dispatches (prefill buckets, decode chunk
fns, continuation/verify programs), an *analytical* cost model —
weight bytes streamed, KV bytes read/written (paged layout and int8
aware), activation bytes, FLOPs — computed from the model config and
the program's static shape, paired with *measured* per-dispatch device
time (the flight recorder already times the dispatch's block-boundary
wait; samples are keyed by program id). ``/attribution`` then reports
achieved-vs-expected per program: the roofline gap decomposes into
named programs with their own rooflines.

**HBM memory ledger** (:func:`memory_ledger`): a live
``hbm_bytes_by_owner`` breakdown — weights, KV pool, sampler state,
device-LRU caches, and ``slack`` (detected limit minus accounted:
compiled programs, XLA scratch, allocator overhead — everything the
engine cannot see from host). Prefix-cache blocks live *inside* the KV
pool arrays, so they are reported as a sub-owner
(``kv_pool_prefix_bytes``), never double-counted: the owner sum plus
slack equals the detected (or table-fallback) capacity exactly.

Cost-model assumptions (documented limits, not hidden ones):

- Decode/verify stream every live weight byte per fused step (the
  batch shares one pass); int8 weights count 1 byte/param with scales
  folded into the measured tree bytes.
- KV traffic counts the *window* actually swept by the program variant
  (the static bucket the jit specialized on), K and V both, one row
  written per new token; int8 KV rows are ``head_dim + 4`` bytes (the
  per-row f32 scale).
- Activation bytes are a lower bound: residual + norm + FFN
  intermediate per layer plus the logits row — enough to matter at a
  128k vocab, deliberately excluding XLA temporaries (those belong to
  the measured-vs-expected *gap*, which is the point).
- FLOPs are ``2 × params`` per token plus the attention window sweep —
  reported for context; the expected time is the HBM-bytes floor
  (decode is bandwidth-bound; a program whose achieved-vs-expected
  ratio is low while FLOP-heavy is compute-bound instead, and
  ``tools/trace_attrib.py`` is the post-mortem for that disagreement).
- MoE engines approximate: every expert's weights count as streamed
  (routed-expert reads are data-dependent; the host cannot know which
  experts fired). Ratios there are a *floor* on efficiency.

Hot-path discipline (graftcheck OBS505, the attribution twin of
OBS503/OBS504): registration and observation run on the engine loop —
plain dict/deque mutation, no locks, no I/O, no device syncs; readers
(:meth:`ProgramLedger.report`, the ``/attribution``/``/memory``
handlers) snapshot with ``dict()``/``list()`` copies and arithmetic
only, so an attribution poll can never perturb — or hang with — the
engine it measures.

Exposure: ``engine.stats()["attribution"]``, the pod ``/attribution``
and ``/memory`` endpoints, the control-plane fan-in beside ``/flight``,
``langstream_serving_hbm_bytes_*`` Prometheus gauges, and the
``engine_top`` attribution panels. See ``docs/OBSERVABILITY.md``.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any

import jax

#: program kinds the ledger knows (mirrors flight PHASES + the
#: continuation split the cost model needs)
PROGRAM_KINDS = ("decode", "prefill", "prefill-continue", "verify")


def tree_device_bytes(tree: Any) -> int:
    """Total device bytes of a pytree of arrays (0 for None/empty).
    Attribute reads only — never a device sync — so it is safe on the
    attribution read path (OBS505)."""
    if tree is None:
        return 0
    total = 0
    for leaf in jax.tree.leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


@dataclasses.dataclass(frozen=True)
class ModelShape:
    """The static model facts every program cost derives from — built
    once per engine so cost registration is pure arithmetic."""

    layers: int
    hidden: int
    heads: int
    kv_heads: int
    head_dim: int
    intermediate: int
    vocab: int
    #: total streamed weight bytes (measured from the live param tree,
    #: so int8 scales and MoE experts are included exactly)
    weight_bytes: int
    #: parameter count (exact for llama trees; estimated from bytes for
    #: MoE) — feeds the FLOPs term only
    param_count: int
    #: bytes per (position, kv-head) cache row, K or V (int8: head_dim
    #: + 4-byte scale; otherwise head_dim × dtype width)
    kv_row_bytes: int
    #: activation dtype width (2 bf16 / 4 f32)
    act_bytes: int


@dataclasses.dataclass(frozen=True)
class ProgramCost:
    """Analytical per-dispatch cost of one compiled program variant."""

    kind: str
    weight_bytes: int
    kv_read_bytes: int
    kv_write_bytes: int
    act_bytes: int
    flops: int
    hbm_gbps: float
    #: tokens the dispatch advances when fully active (normalization)
    tokens: int

    @property
    def total_bytes(self) -> int:
        return (
            self.weight_bytes + self.kv_read_bytes + self.kv_write_bytes
            + self.act_bytes
        )

    def expected_ms(self) -> float:
        """The HBM-bandwidth floor for one dispatch of this program."""
        return self.total_bytes / (self.hbm_gbps * 1e9) * 1e3

    def to_dict(self) -> dict[str, Any]:
        return {
            "weight_bytes": self.weight_bytes,
            "kv_read_bytes": self.kv_read_bytes,
            "kv_write_bytes": self.kv_write_bytes,
            "act_bytes": self.act_bytes,
            "total_bytes": self.total_bytes,
            "flops": self.flops,
            "tokens": self.tokens,
            "expected_ms": round(self.expected_ms(), 4),
        }


def decode_cost(
    shape: ModelShape,
    *,
    slots: int,
    window_rows: int,
    k_steps: int,
    hbm_gbps: float,
) -> ProgramCost:
    """One decode-chunk dispatch: ``k_steps`` fused steps over the full
    ``slots`` batch, each streaming every weight byte and sweeping a
    ``window_rows`` KV window per slot (K and V), writing one new row
    per slot per step."""
    weight = k_steps * shape.weight_bytes
    kv_row = shape.kv_heads * shape.kv_row_bytes * 2  # K and V
    kv_read = k_steps * shape.layers * slots * window_rows * kv_row
    kv_write = k_steps * shape.layers * slots * kv_row
    act = k_steps * slots * shape.act_bytes * (
        shape.layers * (2 * shape.hidden + shape.intermediate) + shape.vocab
    )
    flops = k_steps * slots * (
        2 * shape.param_count
        + 4 * shape.heads * shape.head_dim * window_rows
    )
    return ProgramCost(
        kind="decode",
        weight_bytes=weight,
        kv_read_bytes=kv_read,
        kv_write_bytes=kv_write,
        act_bytes=act,
        flops=flops,
        hbm_gbps=hbm_gbps,
        tokens=k_steps * slots,
    )


def prefill_cost(
    shape: ModelShape,
    *,
    rows: int,
    tokens_per_row: int,
    prefix_rows: int,
    hbm_gbps: float,
) -> ProgramCost:
    """One (possibly batched) prefill dispatch: ``rows`` padded batch
    rows of ``tokens_per_row`` new tokens each. ``prefix_rows`` > 0 is
    the continuation path (suffix prefill against cached history): the
    program additionally reads that many KV rows per batch row."""
    kind = "prefill-continue" if prefix_rows else "prefill"
    weight = shape.weight_bytes  # streamed once for the whole batch
    kv_row = shape.kv_heads * shape.kv_row_bytes * 2
    kv_read = shape.layers * rows * prefix_rows * kv_row
    kv_write = shape.layers * rows * tokens_per_row * kv_row
    act = rows * shape.act_bytes * (
        tokens_per_row * shape.layers
        * (2 * shape.hidden + shape.intermediate)
        + shape.vocab  # logits at the last position only
    )
    # dense FLOPs for every new token, plus the causal attention sweep
    # (each new token attends its prefix: ~tokens/2 new + prefix_rows)
    flops = rows * tokens_per_row * (
        2 * shape.param_count
        + 4 * shape.heads * shape.head_dim
        * (tokens_per_row // 2 + prefix_rows)
    )
    return ProgramCost(
        kind=kind,
        weight_bytes=weight,
        kv_read_bytes=kv_read,
        kv_write_bytes=kv_write,
        act_bytes=act,
        flops=flops,
        hbm_gbps=hbm_gbps,
        tokens=rows,
    )


def verify_cost(
    shape: ModelShape,
    *,
    slots: int,
    window_rows: int,
    drafts: int,
    hbm_gbps: float,
) -> ProgramCost:
    """One speculative verify dispatch: every slot advances ``drafts+1``
    positions in one forward over the full KV window."""
    positions = drafts + 1
    weight = shape.weight_bytes
    kv_row = shape.kv_heads * shape.kv_row_bytes * 2
    kv_read = shape.layers * slots * window_rows * kv_row
    kv_write = shape.layers * slots * positions * kv_row
    act = slots * positions * shape.act_bytes * (
        shape.layers * (2 * shape.hidden + shape.intermediate) + shape.vocab
    )
    flops = slots * positions * (
        2 * shape.param_count
        + 4 * shape.heads * shape.head_dim * window_rows
    )
    return ProgramCost(
        kind="verify",
        weight_bytes=weight,
        kv_read_bytes=kv_read,
        kv_write_bytes=kv_write,
        act_bytes=act,
        flops=flops,
        hbm_gbps=hbm_gbps,
        tokens=slots * positions,
    )


def _pct(sorted_values: list, q: float):
    if not sorted_values:
        return None
    return sorted_values[
        min(len(sorted_values) - 1, int(q * len(sorted_values)))
    ]


class ProgramLedger:
    """Per-program achieved-vs-expected ledger.

    Single writer (the engine loop registers at dispatch preparation and
    observes at each flight record); many readers. Same cross-thread
    contract as the flight recorder: writes are plain dict/deque
    mutations (GIL-atomic container ops, no locks), readers snapshot
    with C-level ``dict()``/``list()`` copies before doing math
    (graftcheck OBS505 polices the read path)."""

    def __init__(self, window: int = 512):
        self.window = window
        # per program id: measured device-ms ring, dispatch count,
        # cumulative device seconds — registered BEFORE the cost entry
        # so a reader iterating _costs always finds the companions
        self._times: dict[str, deque] = {}
        self._dispatches: dict[str, int] = {}
        self._device_s: dict[str, float] = {}
        self._costs: dict[str, ProgramCost] = {}

    # -- writes (engine loop only; arithmetic + container ops) ----------

    def known(self, program: str) -> bool:
        return program in self._costs

    def register(self, program: str, cost: ProgramCost) -> None:
        if program in self._costs:
            return
        self._times[program] = deque(maxlen=self.window)
        self._dispatches[program] = 0
        self._device_s[program] = 0.0
        # published LAST: once visible in _costs, every companion exists
        self._costs[program] = cost

    def observe(self, program: str, device_s: float) -> None:
        """Record one dispatch's measured device wait. Unregistered ids
        are dropped (a registration always precedes the dispatch on the
        same thread, so this only guards torn test doubles)."""
        times = self._times.get(program)
        if times is None:
            return
        times.append(device_s * 1000.0)
        self._dispatches[program] = self._dispatches.get(program, 0) + 1
        self._device_s[program] = (
            self._device_s.get(program, 0.0) + device_s
        )

    # -- reads (snapshot + arithmetic; wait-free by contract) ------------

    def report(self) -> list[dict[str, Any]]:
        """One entry per registered program: the analytical expectation,
        the measured device-time distribution, and their ratio —
        heaviest (by cumulative device time) first."""
        out: list[dict[str, Any]] = []
        for program, cost in list(self._costs.items()):
            samples = sorted(list(self._times.get(program) or ()))
            dispatches = self._dispatches.get(program, 0)
            device_s = self._device_s.get(program, 0.0)
            measured_p50 = _pct(samples, 0.50)
            expected = cost.expected_ms()
            entry: dict[str, Any] = {
                "program": program,
                "kind": cost.kind,
                "dispatches": dispatches,
                "device_s_total": round(device_s, 4),
                "expected": cost.to_dict(),
                "measured_ms_p50": (
                    round(measured_p50, 4) if measured_p50 is not None else None
                ),
                "measured_ms_p95": (
                    round(p95, 4)
                    if (p95 := _pct(samples, 0.95)) is not None
                    else None
                ),
                # the per-program roofline: expected (bytes floor) over
                # measured — 1.0 means the program runs at the assumed
                # HBM bandwidth; low means THIS program owns gap
                "achieved_vs_expected": (
                    round(expected / measured_p50, 6)
                    if measured_p50 else None
                ),
            }
            out.append(entry)
        out.sort(key=lambda e: -e["device_s_total"])
        return out

    def census(self) -> dict[str, int]:
        """Compact program-variant census (``{program: dispatches}``) —
        what bench records stamp so ``perf_diff`` can align rounds
        across code changes."""
        return dict(self._dispatches)


def memory_ledger(
    *,
    weights_bytes: int,
    kv_pool_bytes: int,
    prefix_blocks: int,
    bytes_per_block: int,
    sampler_bytes: int,
    tables_bytes: int,
    limit_bytes: int | None,
    limit_source: str,
    in_transit_bytes: int = 0,
    kv_withheld_bytes: int = 0,
) -> dict[str, Any]:
    """Assemble the ``hbm_bytes_by_owner`` breakdown.

    ``slack`` is the detected limit minus every accounted owner —
    compiled programs, XLA scratch, allocator overhead: resident bytes
    the host cannot attribute. By construction the owner sum (slack
    included) equals ``limit_bytes`` exactly when a limit is known; a
    *negative* slack is reported honestly (the accounting or the
    capacity table is wrong — either way the operator must see it).
    Prefix-cache blocks live inside the KV pool arrays, so they are a
    sub-owner (``kv_pool_prefix_bytes``), never added to the sum — and
    so are budget blocks withheld by an adaptive pool-shrink
    (``kv_pool_withheld_bytes``, docs/RESILIENCE.md): the arrays stay
    allocated through a shrink, only the admission budget moves, so the
    owner sum is identical across shrink/restore by construction."""
    owners: dict[str, int] = {
        "weights": weights_bytes,
        "kv-pool": kv_pool_bytes,
        "sampler-state": sampler_bytes,
        "device-lru": tables_bytes,
        # KV handoff payloads serialized but not yet picked up by the
        # decode pool (docs/DISAGG.md): host-resident, but accounted in
        # the same ledger so a stalled handoff pipeline names its bytes
        "in-transit": in_transit_bytes,
    }
    accounted = sum(owners.values())
    slack = None
    if limit_bytes is not None:
        slack = limit_bytes - accounted
        owners["slack"] = slack
    return {
        "hbm_bytes_by_owner": owners,
        "accounted_bytes": accounted,
        "kv_pool_prefix_bytes": prefix_blocks * bytes_per_block,
        "kv_pool_withheld_bytes": kv_withheld_bytes,
        "limit_bytes": limit_bytes,
        "limit_source": limit_source,
        "slack_bytes": slack,
    }
